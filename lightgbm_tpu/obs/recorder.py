"""Per-iteration JSONL telemetry events + the stats summarizer.

One :class:`TelemetryRecorder` owns one output file and emits exactly
one JSON object per boosting iteration, carrying:

- ``phases``: per-label wall-time deltas for the iteration (diffed from
  ``Timer.snapshot()``; under multi-process SPMD each phase carries
  min/max/mean across processes so chip skew is visible),
- ``recompiles``: jit cache-miss count this iteration plus the running
  total (see :mod:`~lightgbm_tpu.obs.jit_tracker`),
- ``hbm``: ``device.memory_stats()`` gauges, explicit nulls on CPU,
- ``tree``: leaves grown and split-gain sum of the iteration's trees,
- ``eval``: the evaluation tuples the train loop produced (if any).

The recorder is inert until ``attach()`` (called by the train loop once
a telemetry callback or ``LIGHTGBM_TPU_TELEMETRY`` is present): no file
is opened, the Timer stays untouched, and a disabled run writes zero
bytes. Everything it measures also feeds the global
:class:`~lightgbm_tpu.obs.registry.MetricsRegistry`.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .jit_tracker import RecompileWatcher
from .memory import device_memory_stats
from .registry import MetricsRegistry
from .registry import registry as _global_registry
from .schemas import EVENT_NAMES, required_keys

__all__ = ["TelemetryRecorder", "ITERATION_EVENT_KEYS",
           "UnknownEventError",
           "summarize_events", "render_stats_table", "ENTRY_PHASES",
           "summarize_directory", "merge_fleet_summaries",
           "render_fleet_table"]

#: required keys of every iteration event — derived from the
#: single-source schema registry (obs/schemas.py EVENTS, the TPL015
#: contract; semantics documented there and in docs/OBSERVABILITY.md).
#: Re-exported here because the recorder is the canonical emitter and
#: tests/harnesses historically import it from this module.
ITERATION_EVENT_KEYS = required_keys("iteration")


class UnknownEventError(ValueError):
    """A telemetry stream carried an event name the schema registry
    (obs/schemas.py EVENTS) does not declare — a corrupt or
    foreign-version stream. Raised by :func:`summarize_events` instead
    of silently skipping the line (a truncated FINAL line is still
    tolerated at the JSON-parse level, like every stream reader)."""

    def __init__(self, name: str, path: str = ""):
        self.event_name = name
        where = f" in {path}" if path else ""
        super().__init__(
            f"undeclared telemetry event {name!r}{where} — not in the "
            f"obs/schemas.py EVENTS registry")


class TelemetryRecorder:
    """Streams one JSONL event per boosting iteration to ``path``."""

    def __init__(self, path: str,
                 registry: Optional[MetricsRegistry] = None):
        self.path = str(path)
        self.registry = registry if registry is not None \
            else _global_registry
        self._file = None
        self._started = False
        self._engines: List = []
        self._watcher: Optional[RecompileWatcher] = None
        self._phase_base: Dict[str, Dict[str, float]] = {}
        self._prev_timer_enabled: Optional[bool] = None
        self._t0 = 0.0
        self._last_iter_mono = 0.0
        self.events_written = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def active(self) -> bool:
        return self._started

    def attach(self, model) -> None:
        """Bind to a Booster / CVBooster and start recording. Idempotent
        per recorder; a recorder reused across train() calls keeps
        appending to the same file. Under multi-process SPMD every
        process records (the phase aggregation is a collective all ranks
        must join) but only process 0 writes the file — ranks would
        otherwise clobber a shared path."""
        engines = []
        for booster in getattr(model, "boosters", None) or [model]:
            eng = getattr(booster, "_engine", None)
            if eng is not None and eng not in engines:
                engines.append(eng)
        self._engines = engines
        if self._started:
            # reused recorder (second train() call): the file is
            # already open, so a fresh streaming dataset's ingest
            # event can be recorded right away
            self._record_ingest()
            return
        from ..utils.timer import Timer
        self._prev_timer_enabled = Timer.enabled()
        Timer.enable()
        self._phase_base = Timer.snapshot()
        self._watcher = RecompileWatcher()
        self._t0 = time.perf_counter()
        self._last_iter_mono = self._t0
        self._started = True
        try:
            import jax
            is_writer = jax.process_index() == 0
        except Exception:
            is_writer = True
        if is_writer:
            # telemetry must degrade, never break training: an
            # unwritable path (read-only CI mount via the env var, full
            # disk) downgrades to registry-only recording
            try:
                dirname = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(dirname, exist_ok=True)
                self._file = open(self.path, "a", encoding="utf-8")
            except OSError as e:
                from ..utils.log import log_warning
                log_warning(f"telemetry: cannot open {self.path!r} "
                            f"({e}); events will not be written")
                self._file = None
        self._record_ingest()

    def _record_ingest(self) -> None:
        """One ``{"event": "ingest"}`` line per streamed training set
        (lightgbm_tpu/data/): construction ran before the recorder
        attached, so its phase times would otherwise be invisible to
        the per-iteration deltas. Recorded at most once per Dataset —
        a recorder reused across train() calls must not repeat it."""
        if self._file is None:
            # nothing can be written (non-writer rank, or degraded
            # no-file mode): leave the dataset unmarked so a later
            # healthy recorder still gets to record the event
            return
        for eng in self._engines:
            ts = getattr(eng, "train_set", None)
            stats = getattr(ts, "_ingest_stats", None)
            if stats is None or getattr(ts, "_ingest_recorded", False):
                continue
            ts._ingest_recorded = True
            self._write_line({"event": "ingest", **stats})

    def close(self) -> None:
        """Flush and restore the Timer to its pre-attach state. Fault
        events still queued on the engines are drained first — with
        ``nonfinite_policy=raise`` (or a watchdog abort) the exception
        unwinds before the next ``record_iteration``, and the fault
        line must not be lost with it. Every step runs under
        ``finally``: a failing drain or a full disk must still close
        the file and restore the Timer, never leave a recorder
        half-open on the abort path."""
        try:
            self._drain_fault_events()
            self._drain_compile_events()
            self._drain_span_events()
        finally:
            try:
                if self._file is not None:
                    try:
                        self._file.close()
                    except OSError:
                        pass
                    self._file = None
            finally:
                if self._prev_timer_enabled is not None:
                    from ..utils.timer import Timer
                    Timer.enable(self._prev_timer_enabled)
                    self._prev_timer_enabled = None
                self._started = False
                self._engines = []

    # -- event assembly ------------------------------------------------
    def _phase_delta(self, keep_all: bool = False) \
            -> Dict[str, Dict[str, float]]:
        """Per-iteration diff of ``Timer.snapshot()``. ``keep_all``
        retains zero-delta labels — required under multi-process SPMD so
        every rank enters the phase allgather with the same label set
        even on iterations where a phase (e.g. eval) ran on none."""
        from ..utils.timer import Timer
        snap = Timer.snapshot()
        delta: Dict[str, Dict[str, float]] = {}
        for label, cur in snap.items():
            base = self._phase_base.get(label, {"total": 0.0, "count": 0})
            dt = cur["total"] - base["total"]
            dc = int(cur["count"] - base["count"])
            if keep_all or dc > 0 or dt > 0:
                delta[label] = {"total": dt, "count": dc}
        self._phase_base = snap
        return delta

    def _tree_stats(self) -> Dict[str, Optional[float]]:
        leaves = 0
        gain = 0.0
        trees = 0
        for eng in self._engines:
            stats = None
            getter = getattr(eng, "telemetry_tree_stats", None)
            if getter is not None:
                stats = getter()
            if stats is None:
                continue
            trees += stats["trees"]
            leaves += stats["leaves"]
            gain += stats["split_gain_sum"]
        if trees == 0:
            return {"trees": 0, "leaves": None, "split_gain_sum": None}
        return {"trees": trees, "leaves": leaves, "split_gain_sum": gain}

    def _comm_stats(self, tree: Dict) -> Optional[Dict[str, object]]:
        """The iteration's collective-payload record from the first
        distributed engine (models/gbdt.py telemetry_comm_stats),
        reusing the leaves count already fetched for the tree stats so
        telemetry adds no second device round-trip. The reuse is only
        valid when ONE engine is attached — with several, the summed
        leaves would price one engine's reductions by every engine's
        growth, so each engine falls back to its own leaf budget. None
        when every engine trains single-device."""
        leaves = tree.get("leaves") if len(self._engines) == 1 else None
        for eng in self._engines:
            getter = getattr(eng, "telemetry_comm_stats", None)
            if getter is None:
                continue
            stats = getter(leaves)
            if stats is not None:
                return stats
        return None

    def _scan_stats(self) -> Optional[Dict[str, object]]:
        """The iteration's fused scan-window position from the first
        engine that committed one (models/gbdt.py
        telemetry_scan_stats); None on per-iteration paths."""
        for eng in self._engines:
            getter = getattr(eng, "telemetry_scan_stats", None)
            if getter is None:
                continue
            stats = getter()
            if stats is not None:
                return stats
        return None

    @staticmethod
    def _eval_dict(evals: Optional[Sequence]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for entry in evals or []:
            try:
                out[f"{entry[0]}:{entry[1]}"] = float(entry[2])
            except (TypeError, ValueError, IndexError):
                continue
        return out

    def _write_line(self, obj: dict) -> None:
        """One JSONL line; an OSError (ENOSPC etc.) degrades to
        registry-only recording instead of breaking training."""
        if self._file is None:
            return
        try:
            self._file.write(json.dumps(obj) + "\n")
            self._file.flush()
        except OSError as e:
            from ..utils.log import log_warning
            log_warning(f"telemetry: write to {self.path!r} failed "
                        f"({e}); stopping the event stream")
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None

    def _drain_compile_events(self) -> None:
        """Move pending XLA compile records (obs/cost.py: flops/bytes
        cost attribution captured at each entry point's first compile
        per signature) into the JSONL stream. Drained through the same
        locked snapshot-and-clear contract as fault events — a compile
        landing from the batcher thread between a copy and a clear
        must not be lost."""
        try:
            from .cost import drain_compile_events
        except Exception:
            return
        for ev in drain_compile_events():
            self._write_line(ev)

    def _drain_span_events(self) -> None:
        """Move pending trace spans (obs/trace.py: the distributed
        tracing plane's per-iteration, publish and swap spans) into
        the JSONL stream — the same locked snapshot-and-clear drain
        as fault and compile events."""
        try:
            from .trace import drain_span_events
        except Exception:
            return
        for ev in drain_span_events():
            self._write_line(ev)

    def _drain_fault_events(self) -> None:
        """Move fault events (non-finite guard trips, OOM downgrades;
        models/gbdt.py ``fault_log``) into the JSONL stream, plus the
        process-level log (``resilience.faults.FAULT_EVENTS``: init
        retries, watchdog timeouts, distributed injections). All were
        already counted in the metrics registry at record time. Both
        logs are swapped out through ``faults.drain_events`` — the
        locked snapshot-and-clear — because appends can land from
        another thread (a watchdog abort, a second trainer) between a
        bare copy and clear, and that event would be lost forever."""
        try:
            from ..resilience.faults import FAULT_EVENTS, drain_events
        except Exception:
            return
        for eng in self._engines:
            log = getattr(eng, "fault_log", None)
            if not log:
                continue
            for ev in drain_events(log):
                self._write_line(ev)
        if FAULT_EVENTS:
            for ev in drain_events(FAULT_EVENTS):
                self._write_line(ev)

    def record_iteration(self, iteration: int,
                         evals: Optional[Sequence] = None) -> dict:
        """Assemble, register and write the event for one iteration."""
        if not self.active:
            return {}
        try:
            import jax
            multiproc = jax.process_count() > 1
        except Exception:
            multiproc = False
        if multiproc and self._engines:
            # SPMD sanity guard: this event is already a host-level
            # collective sync point, so the cheap [2]-int agreement
            # check rides along (resilience; parallel/spmd.py)
            from ..parallel.spmd import verify_step_consistency
            eng = self._engines[0]
            ntrees = len(getattr(eng, "_models_store", []) or []) \
                + len(getattr(eng, "_pending_dev", []) or [])
            verify_step_consistency(int(iteration), ntrees)
        phases = self._phase_delta(keep_all=multiproc)
        if multiproc:
            from ..parallel.spmd import aggregate_phase_snapshot
            phases = aggregate_phase_snapshot(phases)
        recompile_delta = self._watcher.delta()
        hbm = device_memory_stats()
        tree = self._tree_stats()
        now_mono = time.perf_counter()
        event = {
            "event": "iteration",
            "iteration": int(iteration),
            "wall_time": now_mono - self._t0,
            "phases": phases,
            "recompiles": {"delta": recompile_delta,
                           "total": self._watcher.total},
            "hbm": hbm,
            "tree": tree,
            "eval": self._eval_dict(evals),
            "comm": self._comm_stats(tree),
            "scan": self._scan_stats(),
        }
        self._feed_registry(event)
        # derive the iteration's trace spans (train/iteration parent +
        # phase children, host-gap decomposition on scan iterations)
        # from the deltas just computed — the hot path pays nothing new
        try:
            from .trace import record_iteration_spans
            record_iteration_spans(event, self._last_iter_mono,
                                   now_mono)
        except Exception:
            pass
        self._last_iter_mono = now_mono
        self._drain_fault_events()  # fault lines precede their iteration
        self._drain_compile_events()  # so do the compiles they ran under
        self._drain_span_events()    # and the spans they were timed by
        self._write_line(event)
        self.events_written += 1
        return event

    def _feed_registry(self, event: dict) -> None:
        reg = self.registry
        reg.counter("iterations").inc()
        reg.counter("jit_recompiles").inc(event["recompiles"]["delta"])
        for label, v in event["phases"].items():
            reg.histogram("phase_seconds", phase=label).observe(
                v.get("total", v.get("mean", 0.0)))
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            if event["hbm"].get(key) is not None:
                reg.gauge(f"hbm_{key}").set(event["hbm"][key])
        if event["tree"]["leaves"] is not None:
            reg.histogram("tree_leaves").observe(event["tree"]["leaves"])
            reg.histogram("tree_split_gain_sum").observe(
                event["tree"]["split_gain_sum"])
        comm = event.get("comm")
        if comm:
            reg.counter("comm_bytes",
                        mode=str(comm["parallel_mode"]),
                        wire=str(comm["hist_comm"])).inc(
                comm["payload_bytes"])
        scan = event.get("scan")
        if scan:
            reg.counter("fused_scan_iterations").inc()


# ---------------------------------------------------------------------
# summary side: consumed by `lightgbm_tpu stats <file.jsonl>` and bench
# ---------------------------------------------------------------------

def _stream_lines(path: str, parse):
    """Yield ``parse(line, is_last)`` over non-empty lines with one
    line of lookahead, skipping None results — O(1) memory."""
    with open(path, encoding="utf-8") as fh:
        pending: Optional[str] = None
        for raw in fh:
            line = raw.strip()
            if not line:
                continue
            if pending is not None:
                ev = parse(pending, False)
                if ev is not None:
                    yield ev
            pending = line
        if pending is not None:
            ev = parse(pending, True)
            if ev is not None:
                yield ev


def summarize_events(path: str) -> dict:
    """Fold a telemetry JSONL file into one summary dict.

    A truncated FINAL line is tolerated (skipped with a warning): a
    ``SIGKILL``/preemption can land mid-write, and the stream up to
    that point is exactly what a post-mortem needs. Garbage anywhere
    *before* the last line still raises — that is corruption, not a
    crash artifact."""
    iters = 0
    phases: Dict[str, Dict[str, float]] = {}
    recompiles = 0
    peak_hbm: Optional[int] = None
    leaves = 0
    gain = 0.0
    wall = 0.0
    last_eval: Dict[str, float] = {}
    faults: Dict[str, int] = {}
    ingest: Optional[Dict[str, float]] = None
    serve: Optional[Dict[str, object]] = None
    serve_events = 0
    publishes = 0
    publish: Optional[Dict[str, object]] = None
    comm_bytes = 0
    comm_post_bytes = 0
    comm_last: Optional[Dict[str, object]] = None
    scan_windows = 0
    scan_iterations = 0
    compiles: Dict[str, Dict[str, object]] = {}
    fleet_events = 0
    fleet: Optional[Dict[str, object]] = None
    autoscale: Dict[str, int] = {}
    autoscale_last: Optional[Dict[str, object]] = None
    rollbacks = 0
    rollback_last: Optional[Dict[str, object]] = None
    spans = 0

    def _parse(line: str, is_last: bool) -> Optional[dict]:
        try:
            ev = json.loads(line)
        except ValueError:
            if is_last:
                from ..utils.log import log_warning
                log_warning(
                    f"telemetry: ignoring truncated final line in "
                    f"{path} (the writer was killed mid-write)")
                return None
            raise
        if not isinstance(ev, dict):
            raise ValueError(
                f"telemetry line is not a JSON object: {line[:80]!r}")
        return ev

    # streamed with one line of lookahead (telemetry files can be
    # hundreds of MB): a line is final — and thus allowed to be a
    # truncated crash artifact — only when nothing non-empty follows
    events = _stream_lines(path, _parse)
    for ev in events:
        name = ev.get("event")
        if not isinstance(name, str) or name not in EVENT_NAMES:
            # an undeclared event name means a corrupt or
            # foreign-version stream, not a crash artifact — refuse
            # loudly instead of silently skipping (a truncated FINAL
            # line was already handled above, at the JSON level)
            raise UnknownEventError(str(name), path)
        if ev.get("event") == "fault":
            kind = str(ev.get("kind", "unknown"))
            faults[kind] = faults.get(kind, 0) + 1
            continue
        if ev.get("event") == "ingest":
            ingest = {k: v for k, v in ev.items() if k != "event"}
            continue
        if ev.get("event") == "serve":
            # serve lines carry cumulative counters; the newest one IS
            # the summary (plus how many intervals were recorded)
            serve_events += 1
            serve = {k: v for k, v in ev.items() if k != "event"}
            continue
        if ev.get("event") == "publish":
            # one line per atomic model publication
            # (resilience/publisher.py; docs/PIPELINE.md)
            publishes += 1
            publish = {k: v for k, v in ev.items() if k != "event"}
            continue
        if ev.get("event") == "compile":
            # XLA cost attribution (obs/cost.py): fold per entry point
            # — totals accumulate, the cost-model numbers keep the
            # newest signature's values (re-compiles of one entry are
            # usually shape growth, and the latest shape is the one
            # the phase table measured)
            entry = str(ev.get("entry", "?"))
            slot = compiles.setdefault(
                entry, {"compiles": 0, "wall_ms_total": 0.0,
                        "flops": None, "bytes_accessed": None,
                        "optimal_ms": None, "device_kind": None})
            slot["compiles"] += int(ev.get("compiles", 1) or 1)
            slot["wall_ms_total"] += float(ev.get("wall_ms") or 0.0)
            for key in ("flops", "bytes_accessed", "optimal_ms",
                        "device_kind"):
                if ev.get(key) is not None:
                    slot[key] = ev[key]
            continue
        if ev.get("event") == "fleet":
            # fleet scrape lines carry the supervisor's whole view;
            # the newest one IS the summary
            fleet_events += 1
            fleet = {k: v for k, v in ev.items() if k != "event"}
            continue
        if ev.get("event") == "autoscale":
            # one line per scaling action (resilience/elastic.py):
            # counted per direction, newest kept for provenance
            action = str(ev.get("action", "?"))
            autoscale[action] = autoscale.get(action, 0) + 1
            autoscale_last = {k: v for k, v in ev.items()
                              if k != "event"}
            continue
        if ev.get("event") == "rollback":
            # one line per publication rollback ordered by the fleet
            # supervisor's canary/health guard (docs/RESILIENCE.md)
            rollbacks += 1
            rollback_last = {k: v for k, v in ev.items()
                             if k != "event"}
            continue
        if ev.get("event") == "span":
            # trace spans are counted here and analyzed by
            # `lightgbm_tpu trace <dir>` (obs/trace.py)
            spans += 1
            continue
        if ev.get("event") != "iteration":
            continue
        iters += 1
        wall = max(wall, float(ev.get("wall_time", 0.0)))
        for label, v in ev.get("phases", {}).items():
            slot = phases.setdefault(
                label, {"total": 0.0, "count": 0,
                        "max_skew": 0.0})
            # single-process events carry total; SPMD-aggregated
            # ones carry mean (per-process) + min/max
            slot["total"] += float(v.get("total", v.get("mean", 0.0)))
            slot["count"] += int(v.get("count", 0))
            if "max" in v and "min" in v:
                slot["max_skew"] = max(
                    slot["max_skew"],
                    float(v["max"]) - float(v["min"]))
        recompiles += int(ev.get("recompiles", {}).get("delta", 0))
        hbm = ev.get("hbm", {})
        for key in ("peak_bytes_in_use", "bytes_in_use"):
            if hbm.get(key) is not None:
                peak_hbm = max(peak_hbm or 0, int(hbm[key]))
                break
        tree = ev.get("tree", {})
        if tree.get("leaves") is not None:
            leaves += int(tree["leaves"])
            gain += float(tree.get("split_gain_sum") or 0.0)
        if ev.get("eval"):
            last_eval = ev["eval"]
        if ev.get("comm"):
            comm_last = ev["comm"]
            comm_bytes += int(ev["comm"].get("payload_bytes", 0))
            comm_post_bytes += int(ev["comm"].get(
                "post_reduction_bytes",
                ev["comm"].get("payload_bytes", 0)))
        if ev.get("scan"):
            scan_iterations += 1
            if ev["scan"].get("dispatch"):
                scan_windows += 1
    return {"iterations": iters, "wall_time": wall, "phases": phases,
            "recompiles": recompiles, "peak_hbm_bytes": peak_hbm,
            "total_leaves": leaves, "total_split_gain": gain,
            "last_eval": last_eval, "faults": faults, "ingest": ingest,
            "serve": serve, "serve_events": serve_events,
            "publishes": publishes, "publish": publish,
            "comm_bytes": comm_bytes,
            "comm_post_reduction_bytes": comm_post_bytes,
            "comm": comm_last,
            "scan_windows": scan_windows,
            "scan_iterations": scan_iterations,
            "compiles": compiles,
            "fleet": fleet, "fleet_events": fleet_events,
            "autoscale": autoscale, "autoscale_last": autoscale_last,
            "rollbacks": rollbacks, "rollback": rollback_last,
            "spans": spans}


#: jit entry point -> Timer phase whose per-call mean is the measured
#: counterpart of the entry's cost-model-optimal ms (the live roofline
#: of docs/ROOFLINE.md). Entries without a phase (predict paths) still
#: list their cost numbers, just without a measured column.
ENTRY_PHASES = {
    "gbdt/fused_iter": "boosting/fused_iter",
    "gbdt/fused_scan": "boosting/fused_scan",
    "ops/grow_tree": "tree_learner/grow",
    "parallel/dp_grow": "tree_learner/grow",
    "ranking/lambdarank_grads": "boosting/gradients",
}


def _render_compiles(summary: dict, lines: list) -> None:
    """The ``xla cost`` section: per-entry flops/bytes from the compile
    events plus the roofline comparison — measured per-call phase ms
    against the cost-model optimal at the device peaks."""
    compiles = summary.get("compiles")
    if not compiles:
        return
    phases = summary.get("phases") or {}
    kinds = {v.get("device_kind") for v in compiles.values()
             if v.get("device_kind")}
    lines.append("")
    lines.append(f"xla cost attribution"
                 f"{' (' + ', '.join(sorted(kinds)) + ')' if kinds else ''}:")
    lines.append(f"{'entry':28s} {'compiles':>8s} {'GFLOP':>9s} "
                 f"{'MiB acc':>9s} {'compile ms':>11s} {'opt ms':>8s} "
                 f"{'meas ms':>8s} {'roofline':>9s}")
    for entry, v in sorted(compiles.items()):
        flops = v.get("flops")
        nbytes = v.get("bytes_accessed")
        opt = v.get("optimal_ms")
        meas = None
        phase = phases.get(ENTRY_PHASES.get(entry, ""))
        if phase and phase.get("count"):
            meas = phase["total"] / phase["count"] * 1e3
        roof = (f"{100.0 * opt / meas:8.1f}%"
                if opt is not None and meas else "      n/a")
        lines.append(
            f"{entry:28s} {v.get('compiles', 0):8d} "
            f"{'n/a' if flops is None else '%.3f' % (flops / 1e9):>9s} "
            f"{'n/a' if nbytes is None else '%.1f' % (nbytes / 2**20):>9s} "
            f"{v.get('wall_ms_total', 0.0):11.1f} "
            f"{'n/a' if opt is None else '%.3f' % opt:>8s} "
            f"{'n/a' if meas is None else '%.3f' % meas:>8s} "
            f"{roof}")


def render_stats_table(summary: dict) -> str:
    """The sorted human-readable table behind ``lightgbm_tpu stats``."""
    lines = []
    lines.append(f"iterations           : {summary['iterations']}")
    lines.append(f"wall time            : {summary['wall_time']:.3f} s")
    lines.append(f"jit recompiles       : {summary['recompiles']}")
    hbm = summary["peak_hbm_bytes"]
    lines.append("peak HBM             : " +
                 (f"{hbm / 2**20:.1f} MiB" if hbm is not None else "n/a"))
    ing = summary.get("ingest")
    if ing:
        lines.append(
            f"ingest               : {ing.get('rows', 0)} rows / "
            f"{ing.get('chunks', 0)} chunks of "
            f"{ing.get('chunk_rows', 0)} "
            f"(pass1 {ing.get('pass1_s', 0.0):.3f} s, "
            f"pass2 {ing.get('pass2_s', 0.0):.3f} s)")
    srv = summary.get("serve")
    if srv:
        p50 = srv.get("p50_ms")
        p99 = srv.get("p99_ms")
        rc = srv.get("recompiles") or {}
        lines.append(
            f"serve                : {srv.get('requests_total', 0)} req"
            f" / {srv.get('rows_total', 0)} rows in "
            f"{summary.get('serve_events', 0)} interval(s), last qps "
            f"{srv.get('qps', 0):g}, p50 "
            f"{'n/a' if p50 is None else '%g ms' % p50}, p99 "
            f"{'n/a' if p99 is None else '%g ms' % p99}, swaps "
            f"{srv.get('swaps_total', 0)}, shed "
            f"{srv.get('shed_total', 0)}, recompiles "
            f"{rc.get('total', 0)}, model {srv.get('model', '?')}")
    pub = summary.get("publish")
    if pub:
        sha = str(pub.get("sha256") or "?")
        lines.append(
            f"publish              : {summary.get('publishes', 0)} "
            f"publication(s), last {pub.get('file', '?')} "
            f"(gen {pub.get('generation', '?')}, "
            f"train_auc {pub.get('train_auc', '?')}, "
            f"sha256 {sha[:12]}…)")
    comm = summary.get("comm")
    if comm:
        cb = summary.get("comm_bytes", 0)
        pb = summary.get("comm_post_reduction_bytes", cb)
        lines.append(
            f"comm payload         : {cb / 2**20:.1f} MiB modeled "
            f"({comm.get('parallel_mode', '?')}-parallel, "
            f"hist_comm {comm.get('hist_comm', '?')}, "
            f"{comm.get('split_search', 'gathered')} search, world "
            f"{comm.get('world', '?')}; post-reduction "
            f"{pb / 2**20:.1f} MiB)")
    flt = summary.get("fleet")
    if flt:
        replicas = flt.get("replicas") or flt.get("ranks") or []
        alive = sum(1 for r in replicas if r.get("alive", True))
        extras = ""
        if flt.get("restarts_total") is not None:
            extras += f", restarts {flt['restarts_total']}"
        if flt.get("iteration_skew") is not None:
            extras += f", iter skew {flt['iteration_skew']}"
        lines.append(
            f"fleet                : {alive}/{len(replicas)} "
            f"{flt.get('shape', 'replicas')} up in "
            f"{summary.get('fleet_events', 0)} scrape(s){extras}")
    asc = summary.get("autoscale") or {}
    if asc:
        lines.append(
            f"autoscale            : {asc.get('up', 0)} up / "
            f"{asc.get('down', 0)} down")
    if summary.get("rollbacks"):
        rb = summary.get("rollback") or {}
        bad = str(rb.get("bad_sha") or "?")[:12]
        good = str(rb.get("good_sha") or "?")[:12]
        lines.append(
            f"rollbacks            : {summary['rollbacks']} "
            f"(last: bad {bad} -> good {good})")
    if summary.get("scan_windows"):
        lines.append(
            f"fused scan           : {summary['scan_iterations']} "
            f"iterations in {summary['scan_windows']} window(s) "
            f"(~{summary['scan_iterations'] / summary['scan_windows']:.1f}"
            " iters/dispatch)")
    lines.append(f"leaves grown         : {summary['total_leaves']}")
    lines.append(f"split gain sum       : {summary['total_split_gain']:g}")
    faults = summary.get("faults") or {}
    if faults:
        per_kind = ", ".join(f"{k}={v}" for k, v in sorted(faults.items()))
        lines.append(f"fault events         : {sum(faults.values())} "
                     f"({per_kind})")
    if summary.get("spans"):
        lines.append(f"trace spans          : {summary['spans']} "
                     "(merge: python -m lightgbm_tpu trace <dir>)")
    for key, val in sorted(summary["last_eval"].items()):
        lines.append(f"final {key:15s}: {val:g}")
    phases = summary["phases"]
    if phases:
        grand = sum(v["total"] for v in phases.values()) or 1.0
        lines.append("")
        lines.append(f"{'phase':34s} {'total s':>10s} {'count':>8s} "
                     f"{'mean ms':>10s} {'%':>6s} {'skew s':>8s}")
        for label, v in sorted(phases.items(),
                               key=lambda kv: -kv[1]["total"]):
            cnt = int(v["count"])
            mean_ms = v["total"] / cnt * 1e3 if cnt else 0.0
            lines.append(
                f"{label:34s} {v['total']:10.3f} {cnt:8d} "
                f"{mean_ms:10.3f} {100 * v['total'] / grand:6.1f} "
                f"{v['max_skew']:8.3f}")
    _render_compiles(summary, lines)
    return "\n".join(lines)


# ---------------------------------------------------------------------
# fleet side: a DIRECTORY of telemetry files (one per process) and the
# merged cross-process view behind `lightgbm_tpu stats <dir> --fleet`
# ---------------------------------------------------------------------

#: the stream names the fleet writes: ``x.jsonl`` plus the
#: per-replica ``x.jsonl.rankN`` and supervisor ``x.jsonl.fleet``
#: suffixes — and nothing else, so a rotated ``x.jsonl.gz`` or an
#: editor's ``x.jsonl.swp`` can never abort the whole directory walk
_STREAM_NAME_RE = re.compile(r"\.jsonl(\.rank\d+|\.fleet)?$")


def summarize_directory(directory: str) -> List[Tuple[str, dict]]:
    """``summarize_events`` over every telemetry stream under
    ``directory`` (recursive — the pipeline nests telemetry/ per
    side), sorted by relative path for stable provenance. Files whose
    events are all unknown kinds still appear (an empty summary keeps
    the provenance honest); matched-but-unreadable files raise like
    the single-file path."""
    out: List[Tuple[str, dict]] = []
    for root, _dirs, names in sorted(os.walk(directory)):
        for name in sorted(names):
            if not _STREAM_NAME_RE.search(name):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, directory)
            out.append((rel, summarize_events(path)))
    return out


def merge_fleet_summaries(entries: List[Tuple[str, dict]]) -> dict:
    """Fold per-process summaries into one fleet view: trainer
    iteration/compile totals, summed serve traffic with worst-case
    p99, shed and restart totals — the numbers ROADMAP 3(b)'s
    autoscaler decides on."""
    merged = {
        "files": len(entries),
        "iterations": 0, "recompiles": 0, "compile_ms": 0.0,
        "publishes": 0, "faults": 0,
        "serve_replicas": 0, "requests_total": 0, "rows_total": 0,
        "shed_total": 0, "swaps_total": 0,
        "qps": 0.0, "p99_ms_max": None,
        "restarts_total": 0, "iteration_skew": None,
        "scale_ups": 0, "scale_downs": 0, "rollbacks": 0,
    }
    for _rel, s in entries:
        merged["iterations"] += int(s.get("iterations") or 0)
        merged["recompiles"] += int(s.get("recompiles") or 0)
        for v in (s.get("compiles") or {}).values():
            merged["compile_ms"] += float(v.get("wall_ms_total") or 0)
        merged["publishes"] += int(s.get("publishes") or 0)
        merged["faults"] += sum((s.get("faults") or {}).values())
        srv = s.get("serve")
        if srv:
            merged["serve_replicas"] += 1
            merged["requests_total"] += int(
                srv.get("requests_total") or 0)
            merged["rows_total"] += int(srv.get("rows_total") or 0)
            merged["shed_total"] += int(srv.get("shed_total") or 0)
            merged["swaps_total"] += int(srv.get("swaps_total") or 0)
            merged["qps"] += float(srv.get("qps") or 0.0)
            p99 = srv.get("p99_ms")
            if p99 is not None:
                merged["p99_ms_max"] = max(
                    merged["p99_ms_max"] or 0.0, float(p99))
        flt = s.get("fleet")
        if flt:
            if flt.get("restarts_total") is not None:
                merged["restarts_total"] = max(
                    merged["restarts_total"],
                    int(flt["restarts_total"]))
            if flt.get("iteration_skew") is not None:
                merged["iteration_skew"] = max(
                    merged["iteration_skew"] or 0,
                    int(flt["iteration_skew"]))
        asc = s.get("autoscale") or {}
        merged["scale_ups"] += int(asc.get("up") or 0)
        merged["scale_downs"] += int(asc.get("down") or 0)
        merged["rollbacks"] += int(s.get("rollbacks") or 0)
    return merged


def render_fleet_table(merged: dict) -> str:
    lines = ["fleet (merged view)"]
    lines.append(f"files                : {merged['files']}")
    lines.append(f"iterations           : {merged['iterations']}")
    lines.append(f"jit recompiles       : {merged['recompiles']}")
    if merged["compile_ms"]:
        lines.append(f"compile wall         : "
                     f"{merged['compile_ms'] / 1e3:.3f} s")
    lines.append(f"publishes            : {merged['publishes']}")
    if merged["serve_replicas"]:
        p99 = merged["p99_ms_max"]
        lines.append(
            f"serve fleet          : {merged['serve_replicas']} "
            f"replica(s), {merged['requests_total']} req / "
            f"{merged['rows_total']} rows, qps {merged['qps']:g}, "
            f"worst p99 {'n/a' if p99 is None else '%g ms' % p99}, "
            f"shed {merged['shed_total']}, swaps "
            f"{merged['swaps_total']}")
    if merged.get("scale_ups") or merged.get("scale_downs"):
        lines.append(
            f"autoscale            : {merged['scale_ups']} up / "
            f"{merged['scale_downs']} down")
    if merged.get("rollbacks"):
        lines.append(f"rollbacks            : {merged['rollbacks']}")
    extras = []
    if merged["restarts_total"]:
        extras.append(f"restarts {merged['restarts_total']}")
    if merged["iteration_skew"] is not None:
        extras.append(f"iteration skew {merged['iteration_skew']}")
    if merged["faults"]:
        extras.append(f"faults {merged['faults']}")
    if extras:
        lines.append(f"health               : {', '.join(extras)}")
    return "\n".join(lines)
