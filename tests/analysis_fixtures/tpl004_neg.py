# tpulint fixture: TPL004 negative — donation used correctly.
import jax
import jax.numpy as jnp


def _step(score, grad):
    return score + grad


fused = jax.jit(_step, donate_argnums=(0,))


def train(score, grad):
    before = jnp.sum(score)       # read BEFORE donation: fine
    score = fused(score, grad)    # rebound to the result immediately
    after = jnp.sum(score)        # reads the NEW buffer
    return before, after


def train_loop(score, grads):
    for g in grads:
        score = fused(score, g)   # rebound each iteration
    return score
