"""TPL011 positive: a strong (non-weak) float64 constant in a traced
function. tests/test_ircheck.py traces ``build``'s function under
``jax.experimental.enable_x64`` and runs
``analysis.ircheck.f64_findings`` over the jaxpr; the ``np.float64``
scalar is a committed dtype (``weak_type=False``), so the multiply
lowers as f64 — exactly the widening TPL011 rejects."""

import numpy as np


def build(jax, jnp):
    def fn(x):
        # EXPECT: TPL011
        return x * np.float64(2.5)

    return fn, (jnp.ones((4,), jnp.float32),)
