"""Feature-parallel and voting-parallel tree learners on the 8-device
virtual CPU mesh (FeatureParallelTreeLearner /
VotingParallelTreeLearner; the reference's _test_distributed.py
equivalence pattern)."""

import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary

needs_mesh = pytest.mark.skipif(len(jax.devices()) < 2,
                                reason="needs a multi-device mesh")


def _train(tree_learner, X, y, extra=None, rounds=6):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "tree_learner": tree_learner,
              "metric": "binary_logloss"}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _trees_equal(a, b):
    if len(a._models) != len(b._models):
        return False
    for ta, tb in zip(a._models, b._models):
        if ta.num_leaves != tb.num_leaves:
            return False
        nn = ta.num_nodes
        for fld in ("split_feature", "threshold_bin", "left_child",
                    "right_child"):
            if not np.array_equal(getattr(ta, fld)[:nn],
                                  getattr(tb, fld)[:nn]):
                return False
        if not np.allclose(ta.leaf_value[:ta.num_leaves],
                           tb.leaf_value[:tb.num_leaves],
                           rtol=1e-5, atol=1e-7):
            return False
    return True


@needs_mesh
def test_feature_parallel_matches_serial():
    X, y = make_synthetic_binary(n=4000, f=11, seed=7)
    serial = _train("serial", X, y)
    feat = _train("feature", X, y)
    assert _trees_equal(serial, feat)
    np.testing.assert_allclose(serial.predict(X[:100]),
                               feat.predict(X[:100]),
                               rtol=1e-5, atol=1e-7)


@needs_mesh
def test_voting_parallel_full_vote_matches_data_parallel():
    X, y = make_synthetic_binary(n=4000, f=9, seed=3)
    # 2*top_k >= F elects every feature -> identical to data-parallel
    data = _train("data", X, y)
    voting = _train("voting", X, y, extra={"top_k": 9})
    assert _trees_equal(data, voting)


@needs_mesh
def test_voting_parallel_restricted_vote_trains():
    rs = np.random.RandomState(11)
    X = rs.randn(4000, 16)
    y = ((X[:, 0] + 0.5 * X[:, 3] + 0.25 * X[:, 9]) > 0).astype(float)
    voting = _train("voting", X, y, extra={"top_k": 3}, rounds=10)
    p = voting.predict(X)
    assert np.all(np.isfinite(p))
    # restricted voting must still learn the dominant signal
    assert np.mean((p > 0.5) == (y > 0.5)) > 0.85


@needs_mesh
def test_feature_parallel_with_bagging_and_categoricals():
    rs = np.random.RandomState(5)
    n = 3000
    Xc = rs.randint(0, 6, size=(n, 1)).astype(float)
    Xn = rs.randn(n, 6)
    X = np.hstack([Xc, Xn])
    y = ((Xc[:, 0] % 2 == 0) ^ (Xn[:, 1] > 0)).astype(float)
    extra = {"bagging_fraction": 0.8, "bagging_freq": 1,
             "categorical_feature": [0]}
    serial = lgb.train({"objective": "binary", "num_leaves": 15,
                        "verbosity": -1, "min_data_in_leaf": 5,
                        "tree_learner": "serial", **extra},
                       lgb.Dataset(X, label=y, categorical_feature=[0]),
                       num_boost_round=5)
    feat = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbosity": -1, "min_data_in_leaf": 5,
                      "tree_learner": "feature", **extra},
                     lgb.Dataset(X, label=y, categorical_feature=[0]),
                     num_boost_round=5)
    assert _trees_equal(serial, feat)


@needs_mesh
def test_feature_parallel_unaligned_word_blocks():
    """D does not divide NW and a tail device's clamped window holds
    LIVE features (round-4 review regression): F=34 u8 features -> 9
    packed words, NWl=2 over 8 devices, so device 4's window clamps to
    words [7, 9) while it owns features [32, 34). The signal feature 32
    lives exactly there; feature-parallel must still find it."""
    rs = np.random.RandomState(13)
    n, f = 3000, 34
    X = rs.randn(n, f)
    y = ((X[:, 32] + 0.3 * X[:, 5]) > 0).astype(float)
    serial = _train("serial", X, y)
    feat = _train("feature", X, y)
    assert _trees_equal(serial, feat)
    np.testing.assert_allclose(serial.predict(X[:100]),
                               feat.predict(X[:100]),
                               rtol=1e-5, atol=1e-7)
    # the signal feature must actually be used
    assert any(32 in t.split_feature[:t.num_nodes]
               for t in serial._models)


@needs_mesh
def test_voting_parallel_count_skewed_shards_root_and_quality():
    """One device holds ~90% of the effective rows (VERDICT r4 weak
    #7): rows are IID but objective weights concentrate ~90% of the
    mass on device 0's contiguous shard, leaving the other seven
    ~16 effective rows each. This stresses the local-ballot scaling
    approximations (sc_loc = round(sc*sh_loc/sh), min_data/ndev).

    Contract (matches the reference): PV-Tree elections at DEEP
    leaves are legitimately noisy on near-empty shards — the
    reference's local ballots (voting_parallel_tree_learner.cpp:61)
    degrade identically, so exact tree equality with data-parallel
    is NOT guaranteed (verified: trees agree through several splits,
    then expansion order drifts). What must hold: (a) every tree's
    ROOT search — where shards are least degenerate — elects the
    data-parallel winner (identical root split), and (b) the final
    model's quality matches data-parallel closely."""
    rs = np.random.RandomState(29)
    n, f = 8192, 16
    X = rs.randn(n, f)
    y = ((X[:, 2] + 0.6 * X[:, 7] + 0.3 * X[:, 11]
          + 0.2 * rs.randn(n)) > 0).astype(float)
    w = np.zeros(n)
    w[:1024] = 1.0           # device 0's whole shard
    w[1024::64] = 1.0        # ~112 scattered rows over devices 1-7
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "top_k": 3}  # 2k = 6 of 16
    data = lgb.train(dict(params, tree_learner="data"),
                     lgb.Dataset(X, label=y, weight=w),
                     num_boost_round=8)
    voting = lgb.train(dict(params, tree_learner="voting"),
                       lgb.Dataset(X, label=y, weight=w),
                       num_boost_round=8)
    for i, (ta, tb) in enumerate(zip(data._models, voting._models)):
        assert ta.split_feature[0] == tb.split_feature[0], (
            f"tree {i}: root election lost the data-parallel winner "
            f"({ta.split_feature[0]} vs {tb.split_feature[0]})")
        assert ta.threshold_bin[0] == tb.threshold_bin[0], (
            f"tree {i}: root threshold diverged")
    mask = w > 0
    pd_, pv = data.predict(X[mask]), voting.predict(X[mask])
    acc_d = np.mean((pd_ > 0.5) == (y[mask] > 0.5))
    acc_v = np.mean((pv > 0.5) == (y[mask] > 0.5))
    assert acc_v > acc_d - 0.01, (acc_d, acc_v)


@needs_mesh
def test_voting_parallel_distribution_skew_still_learns():
    """Adversarial DISTRIBUTION skew: rows sorted by the dominant
    feature, so each device sees a narrow slice and no local ballot
    ranks the globally-best feature highly. PV-Tree (and the
    reference's GlobalVoting, voting_parallel_tree_learner.cpp:364)
    assumes IID shards and may elect differently here — exact
    equality with data-parallel is NOT the contract (verified: the
    root picks feature 11 over 2). The model must still learn the
    signal through the elected features."""
    rs = np.random.RandomState(23)
    n, f = 8192, 16
    X = rs.randn(n, f)
    y = ((X[:, 2] + 0.6 * X[:, 7] + 0.3 * X[:, 11]
          + 0.2 * rs.randn(n)) > 0).astype(float)
    order = np.argsort(X[:, 2] + 0.6 * X[:, 7])
    X, y = X[order], y[order]
    voting = _train("voting", X, y, extra={"top_k": 3}, rounds=10)
    p = voting.predict(X)
    assert np.all(np.isfinite(p))
    assert np.mean((p > 0.5) == (y > 0.5)) > 0.9


@needs_mesh
def test_feature_parallel_bundled_matches_serial_bundled():
    """EFB x feature-parallel (round 5): bundle columns window and
    own per device exactly like plain columns — metadata slices
    rebase into window space, candidates mask to owned columns, and
    the winning SplitInfo (already carrying the ORIGINAL member
    feature id) allreduces. Trees must equal single-device bundled
    training exactly."""
    rs = np.random.RandomState(31)
    n, groups, per_group = 4000, 4, 6
    cols, signal = [], np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        block = np.zeros((n, per_group))
        vals = rs.rand(per_group) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    dense = rs.randn(n, 2)
    X = np.hstack(cols + [dense])
    y = (signal + 0.5 * dense[:, 0]
         + 0.3 * rs.randn(n) > np.median(signal)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "enable_bundle": True}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=6)
    feat = lgb.train(dict(params, tree_learner="feature"),
                     lgb.Dataset(X, label=y), num_boost_round=6)
    assert serial._engine.bundle is not None
    assert feat._engine.bundle is not None, "fp EFB did not engage"
    assert feat._engine.mesh is not None
    assert _trees_equal(serial, feat)
    np.testing.assert_allclose(serial.predict(X[:200]),
                               feat.predict(X[:200]),
                               rtol=1e-5, atol=1e-7)


@needs_mesh
def test_voting_parallel_bundled_full_vote_matches_data_bundled():
    """EFB x voting-parallel (round 5): ballots, election and the
    elected-columns exchange all run in bundle-COLUMN space. With
    2*top_k >= #bundle-columns every column is elected, so voting
    must equal bundled data-parallel exactly."""
    rs = np.random.RandomState(33)
    n, groups, per_group = 4096, 3, 5
    cols, signal = [], np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        block = np.zeros((n, per_group))
        vals = rs.rand(per_group) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    dense = rs.randn(n, 2)
    X = np.hstack(cols + [dense])
    y = (signal + 0.5 * dense[:, 0]
         + 0.3 * rs.randn(n) > np.median(signal)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "enable_bundle": True,
              "top_k": 20}
    data = lgb.train(dict(params, tree_learner="data"),
                     lgb.Dataset(X, label=y), num_boost_round=6)
    voting = lgb.train(dict(params, tree_learner="voting"),
                       lgb.Dataset(X, label=y), num_boost_round=6)
    assert data._engine.bundle is not None
    assert voting._engine.bundle is not None, "vp EFB did not engage"
    # identical structure; leaf values match to f32 summation-order
    # noise (the elected-columns exchange sums hist = select+reduce
    # in a different order than data-parallel's direct psum)
    for ta, tb in zip(data._models, voting._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=1e-4, atol=1e-4)
    # restricted vote must still learn (approximation regime)
    tiny = lgb.train(dict(params, tree_learner="voting", top_k=2),
                     lgb.Dataset(X, label=y), num_boost_round=8)
    p = tiny.predict(X)
    assert np.mean((p > 0.5) == (y > 0.5)) > 0.85
