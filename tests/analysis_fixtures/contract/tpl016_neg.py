"""TPL016 negatives: declared families, kinds, labels — including the
f-string-prefix and literal-loop-table idioms the real tree uses."""


def feed(registry, key):
    registry.counter("pings").inc()
    registry.gauge("ping_depth", lane="fast").set(3)
    registry.histogram("ping_ms").observe(0.25)
    # literal-prefix f-string resolves against declared families
    registry.gauge(f"ping_de{key}").set(1)
    # loop-bound names over an inline literal table resolve too
    for fam, val in (("pings", 1),):
        registry.counter(fam).inc(val)
