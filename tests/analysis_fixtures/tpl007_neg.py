# tpulint fixture: TPL007 negative — rank-dependent ARGUMENTS and
# uniform gates are fine; the CFG meet must keep fall-through branches
# pin-free. No EXPECT lines: the engine must report nothing here.
import json

import jax

from lightgbm_tpu.parallel.hostsync import (host_allgather,
                                            host_broadcast_bytes)


def rank_dependent_argument(mappers):
    """The sync_bin_mappers pattern: rank 0 builds the payload under a
    rank branch, then EVERY rank joins the broadcast."""
    payload = None
    if jax.process_index() == 0:
        payload = json.dumps(mappers).encode()
    return host_broadcast_bytes(payload, "ok/broadcast")


def world_size_gate(arr):
    """process_count() is rank-invariant — gating on it is uniform."""
    if jax.process_count() <= 1:
        return arr[None]
    return host_allgather(arr, "ok/world_gate")


def uniform_early_return(arr, enabled):
    if not enabled:
        return None
    return host_allgather(arr, "ok/uniform_flag")


def rank_gated_local_side_effect(arr, path):
    """Rank-gating NON-collective work after the sync is the idiom
    (rank-0-only checkpoint writes)."""
    g = host_allgather(arr, "ok/gather")
    if jax.process_index() == 0:
        with open(path, "wb") as fh:
            fh.write(bytes(g))
    return g


def collective_in_try_body(arr):
    """The try BODY runs on every rank; only handlers diverge."""
    try:
        return host_allgather(arr, "ok/try_body")
    except RuntimeError:
        return None
