"""SHAP feature contributions (TreeSHAP).

Re-design of the reference's PredictContrib path
(/root/reference/src/boosting/gbdt.cpp:640 and the TreeSHAP recursion in
src/io/tree.cpp). Host-side recursive TreeSHAP over the numpy tree arrays;
a batched device implementation is planned once the interaction surface
stabilizes.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["predict_contrib"]


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0,
                 one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElement], unique_depth: int,
                 zero_fraction: float, one_fraction: float,
                 feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) \
            / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight \
            * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth: int,
                 path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) \
                / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) \
                / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction \
                * (unique_depth - i) / (unique_depth + 1)
        else:
            total += path[i].pweight / (
                zero_fraction * (unique_depth - i) / (unique_depth + 1))
    return total


def _tree_shap(tree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    path = [
        _PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                     p.pweight)
        for p in parent_path[:unique_depth]
    ] + [_PathElement() for _ in range(tree.num_leaves + 2 - unique_depth)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction
                                          - el.zero_fraction) \
                * tree.leaf_value[leaf]
        return

    f = int(tree.split_feature[node])
    hot, cold = _decide_children(tree, node, x[f])
    w_node = float(tree.internal_count[node])
    hot_count = _child_count(tree, hot)
    cold_count = _child_count(tree, cold)
    hot_zero_fraction = hot_count / w_node if w_node > 0 else 0.0
    cold_zero_fraction = cold_count / w_node if w_node > 0 else 0.0
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0
    # undo re-used feature occurrences further up the path
    path_index = 0
    while path_index <= unique_depth:
        if path[path_index].feature_index == f:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, x, phi, hot, unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, f)
    _tree_shap(tree, x, phi, cold, unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, f)


def _child_count(tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _decide_children(tree, node: int, v: float):
    if tree.is_categorical_node(node):
        go_left = tree._cat_decision(node, v)
    else:
        go_left = tree._num_decision(node, v)
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    return (l, r) if go_left else (r, l)


def _expected_value(tree) -> float:
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    total = float(tree.internal_count[0])
    if total <= 0:
        return 0.0
    return float(np.sum(tree.leaf_value[: tree.num_leaves]
                        * tree.leaf_count[: tree.num_leaves]) / total)


def predict_contrib(booster, X: np.ndarray, trees, K: int) -> np.ndarray:
    """Per-feature SHAP values + expected-value column, shape
    [n, (F+1)*K] matching LGBM_BoosterPredictForMat contrib layout."""
    n, _ = X.shape
    F = booster.num_feature()
    out = np.zeros((n, (F + 1) * K), np.float64)
    for ti, tree in enumerate(trees):
        k = ti % K
        base = k * (F + 1)
        if tree.num_leaves <= 1:
            out[:, base + F] += float(tree.leaf_value[0])
            continue
        ev = _expected_value(tree)
        for r in range(n):
            phi = np.zeros(F + 1, np.float64)
            _tree_shap(tree, X[r], phi, 0, 0, [], 1.0, 1.0, -1)
            phi[F] += ev
            out[r, base: base + F + 1] += phi
    return out
