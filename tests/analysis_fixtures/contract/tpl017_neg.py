"""TPL017 negatives: declared vars, matching defaults, bare reads."""

import os


def read(env):
    a = os.environ.get("LIGHTGBM_TPU_PING", "1")
    # a declared-default var may still be read bare (caller handles)
    b = os.environ.get("LIGHTGBM_TPU_PING")
    # no-default vars are read bare with a site-local fallback
    c = os.environ.get("LIGHTGBM_TPU_PONG") or "off"
    env["LIGHTGBM_TPU_PONG"] = "on"
    return a, b, c
