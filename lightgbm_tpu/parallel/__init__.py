"""Distributed training over jax.sharding meshes."""
