"""The R/reticulate de-scope must be evidence, not assertion (VERDICT
r4 #9): the examples/r_reticulate/train_predict.R recipe is executed
for real when an R toolchain exists, and its Python API surface is
validated against the package either way so the script cannot rot.
"""

import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "r_reticulate", "train_predict.R")


def test_r_script_uses_only_real_api():
    """Every `lgb$name` symbol the R script touches must exist on the
    package — renames/removals surface here even without R installed."""
    with open(SCRIPT) as f:
        src = f.read()
    symbols = set(re.findall(r"lgb\$(\w+)", src))
    assert symbols, "script should exercise the lgb API"
    missing = [s for s in symbols if not hasattr(lgb, s)]
    assert not missing, f"R script references unknown API: {missing}"


def test_r_script_call_sequence_mirrored_in_python():
    """Mirror of the R script's exact call sequence with the argument
    spellings reticulate would pass (keyword names, R integers ->
    Python ints, R matrix -> numpy float64). Keep in lockstep with
    train_predict.R."""
    rs = np.random.RandomState(7)
    n, f = 2000, 10
    X = rs.randn(n, f)
    y = ((X @ rs.randn(f) + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    X_train, y_train = X[:1500], y[:1500]
    X_valid, y_valid = X[1500:], y[1500:]

    dtrain = lgb.Dataset(X_train, label=y_train)
    dvalid = lgb.Dataset(X_valid, label=y_valid, reference=dtrain)
    record = {}
    params = dict(objective="binary", metric="auc", num_leaves=31,
                  learning_rate=0.1, verbosity=-1)
    bst = lgb.train(params, dtrain, num_boost_round=30,
                    valid_sets=[dvalid],
                    callbacks=[lgb.record_evaluation(record)])
    auc = record["valid_0"]["auc"]
    assert auc[-1] > 0.8

    pred = bst.predict(X_valid)
    model_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "r_example_model.txt")
    bst.save_model(model_path)
    bst2 = lgb.Booster(model_file=model_path)
    assert np.abs(pred - bst2.predict(X_valid)).max() < 1e-6

    clf = lgb.LGBMClassifier(n_estimators=10, num_leaves=15,
                             verbosity=-1)
    clf.fit(X_train, y_train)
    acc = np.mean((clf.predict(X_valid) > 0.5) == (y_valid > 0.5))
    assert acc > 0.8


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="no R toolchain in this image")
def test_r_script_runs_end_to_end(tmp_path):
    env = dict(os.environ, LIGHTGBM_TPU_PATH=REPO,
               RETICULATE_PYTHON=sys.executable)
    r = subprocess.run(["Rscript", SCRIPT], env=env, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "R-reticulate example OK" in r.stdout
