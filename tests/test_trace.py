"""Distributed tracing plane (ISSUE 16, obs/trace.py,
docs/OBSERVABILITY.md "Tracing").

Layers under test:

1. Span recorder: record/drain contract, buffer cap + drop counter,
   the ``{"event": "span"}`` schema, context propagation (explicit,
   env-inherited, and the ``span()`` context manager).
2. Per-iteration derivation: ``record_iteration_spans`` turns one
   telemetry iteration event into a ``train/iteration`` parent plus
   sequential ``phase/*`` children, with the fused-scan host-gap
   decomposition on scan iterations.
3. The ``python -m lightgbm_tpu trace`` CLI: stream merging across
   ``.rankN``/``.fleet`` suffixes, truncated-final-line tolerance vs
   mid-file corruption, cross-process clock-skew correction against
   synthetic skewed streams, Chrome trace-event (Perfetto) export
   schema, named critical-path reconstruction, and the jax-free
   subprocess proof.
4. Propagation through the serve protocol: a request's ``trace``
   field becomes a ``serve/request`` parent with queue-wait /
   batch-window / dispatch / reply children.
5. Env-driven device captures (utils/timer.py EnvCapture):
   ``LIGHTGBM_TPU_TRACE_TO`` whole-run and ``LIGHTGBM_TPU_XPROF``
   iteration-window wiring, plus ``timed()`` staying a shared no-op
   outside any capture.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tests._mp_utils import REPO_DIR  # noqa: E402

from lightgbm_tpu.obs import trace as T  # noqa: E402


# ---------------------------------------------------------------------
# helpers: fabricate span dicts / streams with controlled clocks
# ---------------------------------------------------------------------

def _span(name, mono, dur, *, wall_offset=1_000_000.0, proc="pidX",
          trace_id="t" * 16, span_id=None, parent_id=None, attrs=None):
    """A raw span event whose wall clock is ``mono + wall_offset`` —
    i.e. a process whose monotonic origin sits ``wall_offset`` seconds
    before the shared wall clock."""
    return {"event": "span", "name": name, "trace_id": trace_id,
            "span_id": span_id or T.new_span_id(),
            "parent_id": parent_id, "wall": mono + wall_offset,
            "mono": mono, "dur": dur, "proc": proc,
            "attrs": attrs or {}}


def _write_stream(path, events, *, truncate_tail=None):
    with open(path, "w", encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
        if truncate_tail is not None:
            fh.write(truncate_tail)  # no newline: mid-write crash


# ---------------------------------------------------------------------
# 1. span recorder basics
# ---------------------------------------------------------------------

def test_record_span_schema_and_drain():
    sid = T.record_span("unit/one", 1.0, 2.0,
                        trace_id="a" * 16, attrs={"k": 1})
    pending = T.span_events_snapshot()
    assert len(pending) == 1
    ev = pending[0]
    assert tuple(ev.keys()) == T.SPAN_EVENT_KEYS
    assert ev["event"] == "span"
    assert ev["span_id"] == sid
    assert ev["trace_id"] == "a" * 16
    assert ev["dur"] == pytest.approx(1.0)
    assert ev["attrs"] == {"k": 1}
    assert ev["proc"].startswith("pid")
    # wall/mono are a paired anchor at span start
    assert ev["mono"] == 1.0
    assert ev["wall"] > 0
    drained = T.drain_span_events()
    assert [e["span_id"] for e in drained] == [sid]
    assert T.drain_span_events() == []
    assert T.span_events_snapshot() == []


def test_buffer_cap_drops_then_drain_resets(monkeypatch):
    monkeypatch.setattr(T, "_SPANS_CAP", 8)
    for i in range(12):
        T.record_span("unit/cap", 0.0, 0.1, attrs={"i": i})
    assert len(T.span_events_snapshot()) == 8
    assert T._spans_dropped == 4
    assert len(T.drain_span_events()) == 8
    assert T._spans_dropped == 0
    # a fresh append after the drain lands again
    T.record_span("unit/after", 0.0, 0.1)
    assert len(T.drain_span_events()) == 1


def test_span_contextmanager_inherits_current_context():
    T.set_current_trace("b" * 16, "c" * 16)
    with T.span("unit/child") as h:
        assert h.trace_id == "b" * 16
        assert h.parent_id == "c" * 16
        h.attrs["extra"] = True
    (ev,) = T.drain_span_events()
    assert ev["trace_id"] == "b" * 16
    assert ev["parent_id"] == "c" * 16
    assert ev["attrs"] == {"extra": True}
    assert ev["dur"] >= 0.0


def test_span_contextmanager_roots_fresh_trace_without_context():
    T.set_current_trace(None)
    with T.span("unit/root"):
        pass
    (ev,) = T.drain_span_events()
    assert len(ev["trace_id"]) == 16
    assert ev["parent_id"] is None


def test_context_inherited_from_env(monkeypatch):
    monkeypatch.setenv(T.TRACE_CTX_ENV,
                       T.format_context("d" * 16, "e" * 16))
    monkeypatch.setattr(T, "_current", False)  # force re-parse
    ctx = T.current_context()
    assert ctx == {"trace_id": "d" * 16, "span_id": "e" * 16}


def test_context_env_malformed_is_absent(monkeypatch):
    monkeypatch.setenv(T.TRACE_CTX_ENV, "not-a-context")
    monkeypatch.setattr(T, "_current", False)
    assert T.current_context() is None


# ---------------------------------------------------------------------
# 2. per-iteration span derivation
# ---------------------------------------------------------------------

def test_record_iteration_spans_phases_and_parenting():
    T.set_current_trace("f" * 16, "9" * 16)
    event = {"iteration": 3,
             "phases": {"hist/build": {"total": 0.010, "count": 4},
                        "split/find": {"total": 0.020, "count": 4},
                        "zero/skip": {"total": 0.0, "count": 0}}}
    T.record_iteration_spans(event, 100.0, 100.05)
    evs = T.drain_span_events()
    parent = evs[0]
    assert parent["name"] == "train/iteration"
    assert parent["trace_id"] == "f" * 16
    assert parent["parent_id"] == "9" * 16
    assert parent["attrs"]["iteration"] == 3
    assert "host_gap_s" not in parent["attrs"]  # not a scan iteration
    kids = evs[1:]
    assert [k["name"] for k in kids] == ["phase/hist/build",
                                         "phase/split/find"]
    assert all(k["parent_id"] == parent["span_id"] for k in kids)
    # sequential layout: children tile [t_start, ...) back to back
    assert kids[0]["mono"] == pytest.approx(100.0)
    assert kids[1]["mono"] == pytest.approx(100.010)


def test_record_iteration_spans_scan_host_gap():
    T.set_current_trace(None)
    event = {"iteration": 7, "scan": {"window": 8},
             "phases": {T.FUSED_SCAN_PHASE:
                        {"total": 0.080, "count": 1}}}
    T.record_iteration_spans(event, 0.0, 0.1)
    evs = T.drain_span_events()
    parent = evs[0]
    assert parent["attrs"]["scan"] == {"window": 8}
    # iteration wall 100ms minus 80ms blocking fused_scan = 20ms gap
    assert parent["attrs"]["host_gap_s"] == pytest.approx(0.02)
    # a bare run (no pipeline context) still groups under ONE trace
    assert len(parent["trace_id"]) == 16


def test_fused_scan_phase_is_single_source_of_truth():
    # gbdt.py times its window dispatch under this exact label; the
    # host-gap derivation subtracts it — both import from trace.py
    from lightgbm_tpu.obs.trace import BLOCKING_PHASES, FUSED_SCAN_PHASE
    assert FUSED_SCAN_PHASE == "boosting/fused_scan"
    assert FUSED_SCAN_PHASE in BLOCKING_PHASES
    src = open(os.path.join(
        REPO_DIR, "lightgbm_tpu", "models", "gbdt.py")).read()
    assert "timed(FUSED_SCAN_PHASE)" in src


# ---------------------------------------------------------------------
# 3. trace CLI: loading, skew correction, export, critical paths
# ---------------------------------------------------------------------

def test_load_spans_walks_fleet_suffixes_and_tolerates_tail(tmp_path):
    _write_stream(tmp_path / "run.jsonl",
                  [_span("a", 1.0, 0.1),
                   {"event": "iteration", "iteration": 0}],
                  truncate_tail='{"event": "span", "name": "cut')
    _write_stream(tmp_path / "run.jsonl.rank1", [_span("b", 2.0, 0.1)])
    _write_stream(tmp_path / "run.jsonl.fleet", [_span("c", 3.0, 0.1)])
    (tmp_path / "notes.txt").write_text("not telemetry\n")
    sub = tmp_path / "serve"
    sub.mkdir()
    _write_stream(sub / "replica.jsonl", [_span("d", 4.0, 0.1)])
    spans = T.load_spans(str(tmp_path))
    got = sorted((s["name"], s["_stream"]) for s in spans)
    assert got == [("a", "run.jsonl"), ("b", "run.jsonl.rank1"),
                   ("c", "run.jsonl.fleet"),
                   ("d", os.path.join("serve", "replica.jsonl"))]


def test_load_spans_mid_file_garbage_raises(tmp_path):
    with open(tmp_path / "bad.jsonl", "w") as fh:
        fh.write("{ corrupt not json }\n")
        fh.write(json.dumps(_span("x", 1.0, 0.1)) + "\n")
    with pytest.raises(ValueError, match="malformed telemetry"):
        T.load_spans(str(tmp_path))


def test_clock_skew_correction_synthetic_streams(tmp_path):
    # trainer's monotonic origin is 1e6 s behind wall; the serve
    # replica restarted recently, its origin only 500 s behind — raw
    # mono values are wildly incomparable (publish mono 2000 vs swap
    # mono 7.0) but the corrected timeline must order them properly
    _write_stream(tmp_path / "train.jsonl", [
        _span("publish/model", 2000.0, 0.05,
              wall_offset=1_000_000.0, proc="pid1")])
    _write_stream(tmp_path / "serve.jsonl", [
        _span("swap/apply", 7.0, 0.02,
              wall_offset=1_001_993.25, proc="pid2")])
    spans = T.load_spans(str(tmp_path))
    offsets = T.correct_clock_skew(spans)
    assert len(offsets) == 2
    pub = next(s for s in spans if s["name"] == "publish/model")
    swap = next(s for s in spans if s["name"] == "swap/apply")
    # publish ends wall 1_002_000.05; swap starts wall 1_002_000.25
    gap = swap["t0"] - pub["t1"]
    assert gap == pytest.approx(0.2, abs=1e-6)
    assert swap["t1"] > swap["t0"] > pub["t1"] > pub["t0"]


def test_clock_skew_median_rejects_ntp_step():
    # one span's wall clock stepped 30 s mid-run; the median offset
    # must stick with the majority, not split the difference
    spans = [_span(f"s{i}", 10.0 + i, 0.01, wall_offset=100.0,
                   proc="p")
             for i in range(5)]
    spans.append(_span("stepped", 20.0, 0.01, wall_offset=130.0,
                       proc="p"))
    for s in spans:
        s["_stream"] = "x.jsonl"
    offsets = T.correct_clock_skew(spans)
    assert offsets[("x.jsonl", "p")] == pytest.approx(100.0)


def test_chrome_trace_schema(tmp_path):
    spans = [_span("train/iteration", 1.0, 0.1, proc="p1"),
             _span("serve/request", 2.0, 0.05, proc="p2")]
    spans[0]["_stream"] = "a.jsonl"
    spans[1]["_stream"] = "b.jsonl"
    T.correct_clock_skew(spans)
    doc = T.chrome_trace(spans)
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(metas) == 2 and len(xs) == 2
    assert all(m["name"] == "process_name" for m in metas)
    assert {m["pid"] for m in metas} == {1, 2}
    assert min(e["ts"] for e in xs) == 0.0  # viewer opens at t=0
    for e in xs:
        assert e["dur"] > 0 and e["ts"] >= 0  # microseconds
        assert e["cat"] in ("train", "serve")
        assert "trace_id" in e["args"] and "span_id" in e["args"]
    assert T.chrome_trace([]) == {"traceEvents": [],
                                  "displayTimeUnit": "ms"}


def _lifecycle_streams(tmp_path, *, serve_wall_offset=2_000.0):
    """Synthetic 3-process lifecycle: trainer (iterations + publish),
    serve replica (swap steps), client (request riding its OWN
    trace, joined by model id)."""
    tid = "11" * 8
    pub_sid = "22" * 8
    _write_stream(tmp_path / "train.jsonl", [
        _span("train/iteration", 100.0, 0.1, trace_id=tid,
              proc="pid10", attrs={"iteration": 4}),
        _span("train/iteration", 100.2, 0.1, trace_id=tid,
              proc="pid10", attrs={"iteration": 5}),
        _span("publish/model", 100.4, 0.05, trace_id=tid,
              span_id=pub_sid, proc="pid10",
              attrs={"generation": 2, "file": "m2.txt"})])
    swap = [("swap/validate", 0.50), ("swap/load", 0.56),
            ("swap/stage", 0.62), ("swap/apply", 0.68)]
    _write_stream(tmp_path / "serve.jsonl", [
        _span(name, 7.0 + dt, 0.04, trace_id=tid, parent_id=pub_sid,
              wall_offset=1_000_093.4 + serve_wall_offset, proc="pid20",
              attrs={"model": "gen2"} if name == "swap/apply" else None)
        for name, dt in swap])
    _write_stream(tmp_path / "client.jsonl", [
        _span("serve/request", 8.1, 0.01, trace_id="33" * 8,
              wall_offset=1_000_093.4 + serve_wall_offset, proc="pid20",
              attrs={"model": "gen2", "rows": 4})])
    return tid


def test_critical_path_reconstruction(tmp_path):
    tid = _lifecycle_streams(tmp_path)
    spans = T.load_spans(str(tmp_path))
    T.correct_clock_skew(spans)
    (path,) = T.critical_paths(spans)
    assert path["trace_id"] == tid
    assert path["generation"] == 2
    assert path["model"] == "gen2"
    assert path["complete"] is True
    names = [s["name"] for s in path["steps"] if not s["gap"]]
    assert names == ["train/iteration #5", "publish/model",
                     "swap/validate", "swap/load", "swap/stage",
                     "swap/apply", "serve/request (model gen2)"]
    # every step and the total carry POSITIVE clock-corrected times
    assert all(s["dur_s"] >= 0 for s in path["steps"])
    assert path["total_s"] > 0
    # steps are monotone on the corrected timeline
    t0s = [s["t0"] for s in path["steps"]]
    assert t0s == sorted(t0s)
    text = T.render_critical_paths([path])
    assert "critical path" in text and "generation 2" in text
    assert "INCOMPLETE" not in text


def test_critical_path_incomplete_without_serve(tmp_path):
    _write_stream(tmp_path / "train.jsonl", [
        _span("train/iteration", 1.0, 0.1, attrs={"iteration": 0}),
        _span("publish/model", 1.2, 0.05, attrs={"generation": 0})])
    spans = T.load_spans(str(tmp_path))
    T.correct_clock_skew(spans)
    (path,) = T.critical_paths(spans)
    assert path["complete"] is False
    assert "INCOMPLETE" in T.render_critical_paths([path])


def test_trace_cli_end_to_end(tmp_path, capsys):
    _lifecycle_streams(tmp_path)
    assert T.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "Perfetto" in out
    assert "clock-skew correction" in out
    assert "critical path" in out
    doc = json.load(open(tmp_path / "trace.json"))
    assert doc["traceEvents"]
    # --out redirects the export
    alt = tmp_path / "alt.json"
    assert T.main([str(tmp_path), "--out", str(alt)]) == 0
    assert json.load(open(alt))["traceEvents"]


def test_trace_cli_error_paths(tmp_path, capsys):
    assert T.main(["--help"]) == 0
    assert "usage: python -m lightgbm_tpu trace" in \
        capsys.readouterr().out
    assert T.main([]) == 1
    assert T.main([str(tmp_path / "missing")]) == 1
    empty = tmp_path / "empty"
    empty.mkdir()
    assert T.main([str(empty)]) == 1  # no spans
    assert T.main([str(tmp_path), "--out"]) == 1  # dangling flag


def test_trace_cli_is_jax_free(tmp_path):
    """`python -m lightgbm_tpu trace` must never import jax — it
    post-processes JSONL where no backend may initialize."""
    d = tmp_path / "telem"
    d.mkdir()
    _write_stream(d / "t.jsonl",
                  [_span("train/iteration", 1.0, 0.1,
                         attrs={"iteration": 0})])
    code = (
        "import sys\n"
        "from lightgbm_tpu.obs.trace import main\n"
        f"rc = main([{str(d)!r}])\n"
        "assert rc == 0, rc\n"
        "assert 'jax' not in sys.modules, 'trace CLI imported jax!'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")


# ---------------------------------------------------------------------
# 4. propagation through the serve protocol + publisher manifest
# ---------------------------------------------------------------------

class _DummyForest:
    n_features = 3
    model_id = "dummy-1"

    def predict_raw(self, X):
        return np.zeros((X.shape[0], 1), np.float32)

    def finalize(self, raw, raw_score=False):
        return raw[:, 0]


def test_serve_protocol_span_propagation():
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.daemon import ServeState, handle_request
    b = MicroBatcher(_DummyForest(), batch_window_ms=0.5)
    state = ServeState(b, "dummy-1", "mem")
    try:
        # untraced request: zero span cost
        r = handle_request({"rows": [[1, 2, 3]]}, state)
        assert "predictions" in r
        assert T.drain_span_events() == []
        # traced request: serve/request parent + the 4 stage children
        r = handle_request({"rows": [[1, 2, 3], [4, 5, 6]],
                            "trace": {"trace_id": "a1" * 8,
                                      "span_id": "b2" * 8}}, state)
        assert "predictions" in r
        evs = T.drain_span_events()
        assert [e["name"] for e in evs] == [
            "serve/request", "serve/queue_wait", "serve/batch_window",
            "serve/dispatch", "serve/reply"]
        parent = evs[0]
        assert parent["trace_id"] == "a1" * 8
        assert parent["parent_id"] == "b2" * 8
        assert parent["attrs"] == {"model": "dummy-1", "rows": 2}
        assert all(e["parent_id"] == parent["span_id"]
                   and e["trace_id"] == "a1" * 8 for e in evs[1:])
        assert all(e["dur"] >= 0 for e in evs)
        # a malformed trace field is ignored, not fatal
        r = handle_request({"rows": [[1, 2, 3]], "trace": "bogus"},
                           state)
        assert "predictions" in r
        assert T.drain_span_events() == []
    finally:
        b.close()
        state.close()


def test_publisher_stamps_trace_context_into_manifest(tmp_path):
    from lightgbm_tpu.resilience.publisher import publish_model
    T.set_current_trace("77" * 8, "88" * 8)
    manifest = publish_model("tree\nend of trees\n", str(tmp_path),
                             "m0.txt", metadata={"generation": 0})
    assert manifest["trace"]["trace_id"] == "77" * 8
    evs = T.drain_span_events()
    (pub,) = [e for e in evs if e["name"] == "publish/model"]
    assert pub["trace_id"] == "77" * 8
    assert pub["span_id"] == manifest["trace"]["span_id"]
    assert pub["parent_id"] == "88" * 8
    assert pub["attrs"]["generation"] == 0
    assert pub["attrs"]["attempts"] == 1
    # a manifest published OUTSIDE any trace still self-identifies
    T.set_current_trace(None)
    manifest = publish_model("tree\nend of trees\n", str(tmp_path),
                             "m1.txt")
    assert len(manifest["trace"]["trace_id"]) == 16
    T.drain_span_events()


def test_summarize_events_counts_spans(tmp_path):
    from lightgbm_tpu.obs import render_stats_table, summarize_events
    path = str(tmp_path / "t.jsonl")
    _write_stream(path, [_span("a", 1.0, 0.1), _span("b", 2.0, 0.1)])
    summ = summarize_events(path)
    assert summ["spans"] == 2
    assert "trace spans" in render_stats_table(summ)


# ---------------------------------------------------------------------
# 5. env-driven device captures (LIGHTGBM_TPU_TRACE_TO / _XPROF)
# ---------------------------------------------------------------------

class _FakeTracer:
    """Records enter/exit pairs in place of jax.profiler captures."""

    def __init__(self):
        self.log = []

    def __call__(self, log_dir):
        tracer = self

        class _CM:
            def __enter__(self):
                tracer.log.append(("enter", log_dir))
                return self

            def __exit__(self, *exc):
                tracer.log.append(("exit", log_dir))
                return False

        return _CM()


def test_parse_xprof_spec():
    from lightgbm_tpu.utils.timer import parse_xprof_spec
    assert parse_xprof_spec("/tmp/x:iters=3-7") == ("/tmp/x", 3, 7)
    assert parse_xprof_spec("/tmp/x:iters=4") == ("/tmp/x", 4, 4)
    # windows-ish dirs with colons survive the rsplit
    assert parse_xprof_spec("a:b:iters=0-1") == ("a:b", 0, 1)
    for bad in ("/tmp/x", "/tmp/x:iters=a-b", ":iters=1-2",
                "/tmp/x:iters=5-2", "/tmp/x:iters=-1"):
        with pytest.raises(ValueError):
            parse_xprof_spec(bad)


def test_env_capture_from_env():
    from lightgbm_tpu.utils.timer import EnvCapture
    assert EnvCapture.from_env({}) is None
    cap = EnvCapture.from_env({"LIGHTGBM_TPU_TRACE_TO": "/tmp/t"})
    assert cap._trace_dir == "/tmp/t" and cap._xprof is None
    cap = EnvCapture.from_env(
        {"LIGHTGBM_TPU_XPROF": "/tmp/x:iters=2-3"})
    assert cap._xprof == ("/tmp/x", 2, 3)
    with pytest.raises(ValueError):
        EnvCapture.from_env({"LIGHTGBM_TPU_XPROF": "nope"})


def test_env_capture_whole_run_and_window():
    from lightgbm_tpu.utils.timer import EnvCapture
    fake = _FakeTracer()
    cap = EnvCapture(trace_dir="whole", xprof=("win", 2, 3),
                     _tracer=fake)
    cap.before_iteration(0)
    assert fake.log == [("enter", "whole")]  # window not armed yet
    cap.after_iteration(0)
    cap.before_iteration(2)
    assert ("enter", "win") in fake.log
    cap.after_iteration(2)       # i < last: window stays open
    assert ("exit", "win") not in fake.log
    cap.before_iteration(3)
    cap.after_iteration(3)       # i == last: window closes, disarms
    assert fake.log.count(("exit", "win")) == 1
    cap.before_iteration(4)      # never re-armed
    assert fake.log.count(("enter", "win")) == 1
    cap.close()
    assert fake.log[-1] == ("exit", "whole")
    cap.close()                  # idempotent
    assert fake.log.count(("exit", "whole")) == 1


def test_env_capture_close_finalizes_open_window():
    from lightgbm_tpu.utils.timer import EnvCapture
    fake = _FakeTracer()
    cap = EnvCapture(xprof=("win", 0, 100), _tracer=fake)
    cap.before_iteration(0)
    cap.after_iteration(0)       # window still open (last=100)
    cap.close()                  # exception-path finalization
    assert fake.log == [("enter", "win"), ("exit", "win")]


def test_timed_is_shared_noop_outside_any_capture():
    from lightgbm_tpu.utils import timer as tm
    assert not tm.Timer._enabled
    assert tm.timed("anything") is tm._NULL


@pytest.mark.slow
def test_timed_annotates_only_while_capture_live(tmp_path):
    """The TRACE_TO satellite: inside a live trace_to capture the
    SAME timed() call switches from the shared no-op to the
    TraceAnnotation-emitting path; after the capture it reverts."""
    from lightgbm_tpu.utils import timer as tm
    assert tm.timed("x") is tm._NULL
    with tm.trace_to(str(tmp_path / "prof")):
        cm = tm.timed("x")
        assert cm is not tm._NULL
        with cm:
            pass
    assert tm.timed("x") is tm._NULL
    # the capture actually materialized profile artifacts
    assert any((tmp_path / "prof").rglob("*"))


def test_span_keys_are_the_schema_registry():
    """Satellite of the contract-lint PR: SPAN_EVENT_KEYS is a derived
    view of the single-source schema registry (obs/schemas.py)."""
    from lightgbm_tpu.obs import schemas
    assert T.SPAN_EVENT_KEYS == \
        tuple(schemas.EVENTS["span"]["required"])
