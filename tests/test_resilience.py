"""Fault-tolerant boosting (resilience/): checkpoint/auto-resume
determinism, atomic snapshot/model writes, corrupted-snapshot fallback,
the non-finite guard policies (driven by the deterministic
fault-injection harness), graceful OOM degradation, and the SPMD step
guard's single-process contract.

The acceptance bar (ISSUE 2): a SIGKILLed-and-resumed run must produce
a model string byte-identical to the uninterrupted run on CPU, injected
NaN gradients must trigger the configured policy with a telemetry fault
event, and no snapshot is ever observable partially written.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.resilience import (CheckpointError, FaultPlan,
                                     checkpoint, list_snapshots,
                                     load_latest_snapshot, load_snapshot)

_DIR = os.path.dirname(os.path.abspath(__file__))


def _regression_data(n=600, f=8, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = X @ rs.randn(f) + 0.1 * rs.randn(n)
    return X, y


# sampling + per-tree column RNG on: resume must replay both the
# device-keyed bagging cache and the host feature-sampling RNG
_PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
           "min_data_in_leaf": 5, "bagging_fraction": 0.7,
           "bagging_freq": 2, "feature_fraction": 0.8, "seed": 3}


def _ds(X, y):
    return lgb.Dataset(X, label=y)


# ---------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------

def test_resume_equivalence_byte_identical(tmp_path):
    """train 14 == train 7 + resume 7: model strings byte-identical
    (CPU backend), including bagging/feature-fraction RNG state."""
    X, y = _regression_data()
    full = lgb.train(_PARAMS, _ds(X, y), num_boost_round=14)
    ck = str(tmp_path / "ck")
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=7,
              callbacks=[checkpoint(ck)])
    resumed = lgb.train(_PARAMS, _ds(X, y), num_boost_round=14,
                        resume_from=ck)
    assert resumed.current_iteration() == 14
    assert resumed.model_to_string() == full.model_to_string()


def test_resume_noop_when_target_already_reached(tmp_path):
    X, y = _regression_data()
    ck = str(tmp_path / "ck")
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=6,
              callbacks=[checkpoint(ck)])
    resumed = lgb.train(_PARAMS, _ds(X, y), num_boost_round=6,
                        resume_from=ck)
    assert resumed.current_iteration() == 6


def test_resume_from_empty_dir_trains_from_scratch(tmp_path):
    X, y = _regression_data()
    ck = str(tmp_path / "nothing-here")
    bst = lgb.train(_PARAMS, _ds(X, y), num_boost_round=4,
                    resume_from=ck)
    assert bst.current_iteration() == 4


def test_checkpoint_retention_and_final_snapshot(tmp_path):
    ck = tmp_path / "ck"
    X, y = _regression_data()
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=9,
              callbacks=[checkpoint(str(ck), every_n_iters=2, keep=3)])
    names = sorted(p.name for p in ck.iterdir())
    # every_n=2 writes 2,4,6,8 plus the final iteration 9; keep=3
    assert names == ["ckpt_00000006.npz", "ckpt_00000008.npz",
                     "ckpt_00000009.npz"]
    assert not [n for n in names if n.endswith(".tmp")]
    for n in names:
        load_snapshot(str(ck / n))  # all retained snapshots validate


def test_corrupted_latest_falls_back_to_previous(tmp_path):
    """A truncated newest snapshot must not break resume: the loader
    falls back to the previous complete one, and the resumed run still
    matches the uninterrupted model byte-for-byte."""
    ck = tmp_path / "ck"
    X, y = _regression_data()
    full = lgb.train(_PARAMS, _ds(X, y), num_boost_round=8)
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=6,
              callbacks=[checkpoint(str(ck), keep=10)])
    latest = ck / "ckpt_00000006.npz"
    blob = latest.read_bytes()
    latest.write_bytes(blob[: len(blob) // 3])  # truncate mid-zip
    snap = load_latest_snapshot(str(ck))
    assert snap is not None and snap["iteration"] == 5
    with pytest.raises(CheckpointError):
        load_snapshot(str(latest))
    resumed = lgb.train(_PARAMS, _ds(X, y), num_boost_round=8,
                        resume_from=str(ck))
    assert resumed.model_to_string() == full.model_to_string()


def test_resume_across_natural_growth_stall_byte_identical(tmp_path):
    """Constant labels exhaust growth at iteration 0; the uninterrupted
    run stops at the one-late no-growth check. A resume from the
    stalled iteration's snapshot must stop at the same point instead of
    regrowing an extra constant tree (the snapshot persists the
    'stalled' marker, review regression)."""
    rs = np.random.RandomState(5)
    X = rs.randn(300, 6)
    y = np.ones(300)
    params = {"objective": "regression", "verbosity": -1,
              "feature_fraction": 0.8, "seed": 1}
    full = lgb.train(params, _ds(X, y), num_boost_round=10)
    assert full.current_iteration() == 1  # stalls immediately
    ck = str(tmp_path / "ck")
    lgb.train(params, _ds(X, y), num_boost_round=10,
              callbacks=[checkpoint(ck)])
    snap = load_latest_snapshot(ck)
    assert snap["stalled"] is True
    resumed = lgb.train(params, _ds(X, y), num_boost_round=10,
                        resume_from=ck)
    assert resumed.current_iteration() == 1
    assert resumed.model_to_string() == full.model_to_string()


def test_checkpoint_env_var_installs_callback_and_resumes(tmp_path,
                                                          monkeypatch):
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("LIGHTGBM_TPU_CHECKPOINT", ck)
    X, y = _regression_data()
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=5)
    assert load_latest_snapshot(ck)["iteration"] == 5
    resumed = lgb.train(_PARAMS, _ds(X, y), num_boost_round=9)
    assert resumed.current_iteration() == 9
    monkeypatch.delenv("LIGHTGBM_TPU_CHECKPOINT")
    full = lgb.train(_PARAMS, _ds(X, y), num_boost_round=9)
    assert resumed.model_to_string() == full.model_to_string()


def test_restore_rejects_wrong_dataset_shape(tmp_path):
    ck = str(tmp_path / "ck")
    X, y = _regression_data()
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=3,
              callbacks=[checkpoint(ck)])
    X2, y2 = _regression_data(n=300)
    # the dataset fingerprint (n/F/label digest) fires before the
    # score-shape backstop ever sees the [K, n] mismatch
    with pytest.raises(LightGBMError, match="different training data"):
        lgb.train(_PARAMS, _ds(X2, y2), num_boost_round=5,
                  resume_from=ck)


def test_save_model_is_atomic(tmp_path, monkeypatch):
    """A crash mid-save must leave the previous complete model file in
    place and no tmp litter (tmp + os.replace, utils/atomic.py)."""
    X, y = _regression_data(n=300)
    bst = lgb.train({"objective": "regression", "verbosity": -1},
                    _ds(X, y), num_boost_round=2)
    out = tmp_path / "model.txt"
    bst.save_model(str(out))
    original = out.read_text()
    assert lgb.Booster(model_file=str(out)).num_trees() == 2

    import lightgbm_tpu.utils.atomic as atomic_mod
    real_replace = atomic_mod.os.replace

    def boom(src, dst):
        raise OSError("simulated crash before publish")

    monkeypatch.setattr(atomic_mod.os, "replace", boom)
    with pytest.raises(OSError, match="simulated crash"):
        bst.save_model(str(out))
    monkeypatch.setattr(atomic_mod.os, "replace", real_replace)
    assert out.read_text() == original  # old file intact
    assert [p.name for p in tmp_path.iterdir()] == ["model.txt"]  # no tmp


# ---------------------------------------------------------------------
# non-finite guard x fault injection
# ---------------------------------------------------------------------

def _binary_data(n=500, f=6, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    return X, (X[:, 0] > 0).astype(float)


_GUARD = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5}


def test_nan_grad_raise_policy_fused(tmp_path, monkeypatch):
    """Default policy: injected NaN gradients abort with a clear error
    (one iteration late on the fused path) and the telemetry stream
    carries the fault event."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@2")
    tpath = str(tmp_path / "t.jsonl")
    X, y = _binary_data()
    with pytest.raises(LightGBMError, match="non-finite gradients"):
        lgb.train(_GUARD, _ds(X, y), num_boost_round=6,
                  callbacks=[lgb.telemetry(tpath)])
    events = [json.loads(l) for l in open(tpath) if l.strip()]
    faults = [e for e in events if e["event"] == "fault"]
    assert faults and faults[0]["kind"] == "nonfinite"
    assert faults[0]["iteration"] == 2
    assert faults[0]["action"] == "raise"


def test_nan_grad_raise_policy_eager_exact_iteration(monkeypatch):
    """Eager path (valid sets present) already syncs per iteration, so
    the raise lands at the exact injected iteration."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@3")
    X, y = _binary_data()
    dv = lgb.Dataset(X[:100], label=y[:100])
    with pytest.raises(LightGBMError, match="at iteration 3"):
        lgb.train(_GUARD, _ds(X, y), num_boost_round=6, valid_sets=[dv])


def test_nan_grad_skip_tree_policy(monkeypatch):
    """skip_tree: the poisoned iteration's tree is demoted to a no-op
    constant, training continues, and the final model is finite."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@2")
    X, y = _binary_data()
    bst = lgb.train({**_GUARD, "nonfinite_policy": "skip_tree"},
                    _ds(X, y), num_boost_round=6)
    assert bst.current_iteration() == 6
    leaves = [t.num_leaves for t in bst._models]
    assert leaves[2] == 1 and all(l > 1 for i, l in enumerate(leaves)
                                  if i != 2)
    assert np.all(np.isfinite(bst.predict(X[:50])))


def test_nan_hess_clamp_policy(tmp_path, monkeypatch):
    """clamp: NaN/Inf replaced with finite values, every tree still
    grows, and the fault is observable in telemetry."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_hess@1")
    tpath = str(tmp_path / "t.jsonl")
    X, y = _binary_data()
    bst = lgb.train({**_GUARD, "nonfinite_policy": "clamp"},
                    _ds(X, y), num_boost_round=5,
                    callbacks=[lgb.telemetry(tpath)])
    assert bst.current_iteration() == 5
    assert all(np.all(np.isfinite(t.leaf_value[: t.num_leaves]))
               for t in bst._models)
    assert np.all(np.isfinite(bst.predict(X[:50])))
    events = [json.loads(l) for l in open(tpath) if l.strip()]
    faults = [e for e in events if e["event"] == "fault"]
    assert faults and faults[0]["action"] == "clamp"
    assert "hessians" in faults[0]["detail"]


def test_skip_tree_does_not_end_training_eager(monkeypatch):
    """Eager path: a skip_tree demotion must not be mistaken for
    'no more leaves to split' (which ends training)."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@1")
    X, y = _binary_data()
    dv = lgb.Dataset(X[:100], label=y[:100])
    bst = lgb.train({**_GUARD, "nonfinite_policy": "skip_tree"},
                    _ds(X, y), num_boost_round=5, valid_sets=[dv])
    assert bst.current_iteration() == 5
    assert bst._models[1].num_leaves == 1


def test_skip_tree_with_checkpoint_drain_does_not_end_training(
        tmp_path, monkeypatch):
    """The checkpoint callback drains the guard queue out-of-band every
    iteration; the sticky fault marker must survive that drain so the
    next update() does not misread the demoted 1-leaf tree as 'no more
    leaves to split' and end the run early (review regression)."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@2")
    ck = str(tmp_path / "ck")
    X, y = _binary_data()
    bst = lgb.train({**_GUARD, "nonfinite_policy": "skip_tree"},
                    _ds(X, y), num_boost_round=6,
                    callbacks=[checkpoint(ck)])
    assert bst.current_iteration() == 6
    assert [t.num_leaves for t in bst._models].count(1) == 1


def test_resume_refuses_different_training_data(tmp_path):
    """Same-shape different data must not silently continue another
    run's trees (the hands-off env mode hazard): the snapshot's dataset
    fingerprint mismatch raises instead."""
    ck = str(tmp_path / "ck")
    X, y = _regression_data()
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=3,
              callbacks=[checkpoint(ck)])
    with pytest.raises(LightGBMError, match="different training data"):
        lgb.train(_PARAMS, _ds(X, -y), num_boost_round=5,
                  resume_from=ck)


def test_poisoned_iteration_never_becomes_a_snapshot(tmp_path,
                                                     monkeypatch):
    """Checkpoint x raise policy on the fused path: the snapshot write
    drains the one-iteration-late guard flags first, so the NaN
    iteration raises BEFORE its poisoned trees/score are persisted —
    the newest snapshot stays the last clean iteration and resume makes
    progress instead of restoring poison forever."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@3")
    ck = str(tmp_path / "ck")
    X, y = _binary_data()
    with pytest.raises(LightGBMError, match="non-finite"):
        lgb.train(_GUARD, _ds(X, y), num_boost_round=8,
                  callbacks=[checkpoint(ck, keep=10)])
    snap = load_latest_snapshot(ck)
    assert snap is not None and snap["iteration"] == 3
    assert np.all(np.isfinite(snap["score"]))
    monkeypatch.delenv("LIGHTGBM_TPU_FAULT_INJECT")
    resumed = lgb.train(_GUARD, _ds(X, y), num_boost_round=8,
                        resume_from=ck)
    assert resumed.current_iteration() == 8
    assert all(t.num_leaves > 1 for t in resumed._models[3:])


def test_fault_plan_parsing():
    plan = FaultPlan("nan_grad@7, oom@3,oom@3,kill@12")
    assert plan.active
    assert plan.iters("nan_grad") == (7,)
    assert plan.iters("oom") == (3, 3)
    assert plan.fires("kill", 12) and not plan.fires("kill", 11)
    assert plan.take("oom", 3) and plan.take("oom", 3)
    assert not plan.take("oom", 3)  # consumed
    assert not FaultPlan("").active
    with pytest.raises(ValueError, match="unknown fault-injection"):
        FaultPlan("explode@3")
    with pytest.raises(ValueError, match="kind@iteration"):
        FaultPlan("nan_grad:3")


# ---------------------------------------------------------------------
# OOM degradation
# ---------------------------------------------------------------------

def test_oom_degrades_mxu_to_scatter(tmp_path, monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "oom@1")
    tpath = str(tmp_path / "t.jsonl")
    X, y = _binary_data()
    bst = lgb.train({**_GUARD, "hist_method": "mxu"}, _ds(X, y),
                    num_boost_round=4, callbacks=[lgb.telemetry(tpath)])
    assert bst.current_iteration() == 4
    assert bst._engine.grow_cfg.hist_method == "scatter"
    events = [json.loads(l) for l in open(tpath) if l.strip()]
    oom = [e for e in events if e["event"] == "fault"
           and e["kind"] == "oom"]
    assert oom and "scatter" in oom[0]["action"]


def test_oom_shrinks_histogram_pool_then_fails_cleanly(monkeypatch):
    """Already on scatter: the degradation ladder halves the histogram
    pool; an OOM that persists past the last rung surfaces as a clear
    LightGBMError, not a raw XlaRuntimeError."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "oom@0,oom@0")
    X, y = _binary_data()
    bst = lgb.train({**_GUARD, "num_leaves": 8}, _ds(X, y),
                    num_boost_round=2)
    # two injected OOMs -> two pool halvings (8 -> 4 -> 2), then done
    assert bst._engine.grow_cfg.hist_pool_slots == 2
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT",
                       "oom@0,oom@0,oom@0,oom@0")
    with pytest.raises(LightGBMError, match="no degradation left"):
        lgb.train({**_GUARD, "num_leaves": 4}, _ds(X, y),
                  num_boost_round=2)


# ---------------------------------------------------------------------
# SPMD guard + CLI
# ---------------------------------------------------------------------

def test_verify_step_consistency_single_process_noop():
    from lightgbm_tpu.parallel.spmd import verify_step_consistency
    verify_step_consistency(3, 3)  # must be a free no-op


def test_cli_checkpoints_lists_and_flags_corrupt(tmp_path, capsys):
    from lightgbm_tpu.cli import main
    ck = tmp_path / "ck"
    X, y = _regression_data(n=300)
    lgb.train(_PARAMS, _ds(X, y), num_boost_round=4,
              callbacks=[checkpoint(str(ck), keep=10)])
    bad = ck / "ckpt_00000004.npz"
    bad.write_bytes(bad.read_bytes()[:64])
    assert main(["checkpoints", str(ck)]) == 0
    out = capsys.readouterr().out
    assert "corrupt" in out
    assert "resume target: iteration 3" in out
    rows = list_snapshots(str(ck))
    assert [r["status"] for r in rows] == ["ok", "ok", "ok", "corrupt"]
    assert main(["checkpoints", str(tmp_path / "missing")]) == 1


# ---------------------------------------------------------------------
# SIGKILL mid-train -> auto-resume (the acceptance scenario)
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(420)
def test_sigkill_mid_train_resumes_byte_identical(tmp_path):
    """Kill-and-resume determinism end to end: a worker SIGKILLed at
    iteration 12 of 20 leaves only complete snapshots behind; rerunning
    it auto-resumes and saves a model byte-identical to an
    uninterrupted worker's. Also proves atomicity under a real hard
    kill: every snapshot in the directory still validates."""
    env = dict(os.environ)
    ck = str(tmp_path / "ck")
    killed_model = str(tmp_path / "model_killed.txt")
    env["LIGHTGBM_TPU_CHECKPOINT"] = ck
    env["LIGHTGBM_TPU_FAULT_INJECT"] = "kill@12"
    worker = [sys.executable, os.path.join(_DIR, "ckpt_worker.py")]

    p = subprocess.run(worker + [killed_model], env=env,
                       capture_output=True, timeout=300)
    assert p.returncode == -signal.SIGKILL, p.stdout.decode()
    assert not os.path.exists(killed_model)

    # no partially-written snapshot is ever observable
    rows = list_snapshots(ck)
    assert rows and all(r["status"] == "ok" for r in rows)
    assert max(r["iteration"] for r in rows) == 12

    env.pop("LIGHTGBM_TPU_FAULT_INJECT")
    p = subprocess.run(worker + [killed_model], env=env,
                       capture_output=True, timeout=300)
    assert p.returncode == 0, p.stdout.decode() + p.stderr.decode()
    assert b"WORKER DONE iterations=20" in p.stdout

    env2 = dict(os.environ)
    env2["LIGHTGBM_TPU_CHECKPOINT"] = str(tmp_path / "ck2")
    clean_model = str(tmp_path / "model_clean.txt")
    p = subprocess.run(worker + [clean_model], env=env2,
                       capture_output=True, timeout=300)
    assert p.returncode == 0, p.stdout.decode() + p.stderr.decode()

    with open(killed_model) as a, open(clean_model) as b:
        assert a.read() == b.read()


# ---------------------------------------------------------------------
# hostsync kv bookkeeping: the _pending_delete lock (ISSUE 5 / TPL008)
# ---------------------------------------------------------------------

class _FakeKvClient:
    """In-memory stand-in for the coordination-service client: enough
    surface for _kv_exchange, with a thread-safe ledger of published
    and deleted keys so the pending-delete bookkeeping is auditable."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.store = {}
        self.published = []
        self.deleted = []

    def key_value_set_bytes(self, key, value):
        with self._lock:
            self.store[key] = value
            self.published.append(key)

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        with self._lock:
            if key in self.store:
                return self.store[key]
        raise RuntimeError(f"DEADLINE_EXCEEDED: {key}")

    def key_value_delete(self, key):
        with self._lock:
            self.store.pop(key, None)
            self.deleted.append(key)

    def wait_at_barrier(self, key, timeout_ms):
        return None


def _single_rank_kv(monkeypatch):
    """Wire _kv_exchange to a fake client in a 1-process world (every
    read is our own key, so no blocking)."""
    import jax

    from lightgbm_tpu.parallel import hostsync

    client = _FakeKvClient()
    monkeypatch.setattr(hostsync, "_kv_client", lambda: client)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    # drain state other tests may have left
    with hostsync._pending_lock:
        hostsync._pending_delete[:] = []
    return client, hostsync


def test_kv_pending_delete_flushed_on_next_gather(monkeypatch):
    """Small keys are deleted lazily: epoch E's key is flushed when a
    LATER gather completes (the epoch argument proves every rank is
    past E). The copy-under-lock refactor must preserve exactly that
    protocol."""
    client, hostsync = _single_rank_kv(monkeypatch)
    hostsync._kv_exchange("unit/a", b"x", gather=True)
    with hostsync._pending_lock:
        assert len(hostsync._pending_delete) == 1
    first_key = hostsync._pending_delete[0]
    assert client.deleted == []

    hostsync._kv_exchange("unit/b", b"y", gather=True)
    assert client.deleted == [first_key]
    with hostsync._pending_lock:
        assert len(hostsync._pending_delete) == 1
        assert hostsync._pending_delete[0] != first_key


def test_kv_large_payloads_barrier_and_delete_eagerly(monkeypatch):
    client, hostsync = _single_rank_kv(monkeypatch)
    big = b"z" * (hostsync._KV_CLEANUP_BYTES + 1)
    hostsync._kv_exchange("unit/big", big, gather=True)
    assert client.deleted == client.published  # deleted after barrier
    with hostsync._pending_lock:
        assert hostsync._pending_delete == []


def test_kv_pending_delete_no_key_lost_across_threads(monkeypatch):
    """The TPL008 race made concrete: concurrent exchanges (two
    trainers, successive watchdog workers) must neither lose a pending
    key (a coordinator store leak) nor double-delete one. With the
    lock, every published small key is deleted exactly once or still
    queued at the end."""
    import threading

    client, hostsync = _single_rank_kv(monkeypatch)
    n_threads, per_thread = 6, 40
    start = threading.Barrier(n_threads)
    errors = []

    def worker(tid):
        start.wait()
        try:
            for i in range(per_thread):
                hostsync._kv_exchange(f"unit/t{tid}/{i}", b"k",
                                      gather=True)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    with hostsync._pending_lock:
        remaining = list(hostsync._pending_delete)
    deleted = list(client.deleted)
    assert len(deleted) == len(set(deleted)), "a key was deleted twice"
    assert sorted(deleted + remaining) == sorted(set(client.published)), (
        "pending-delete bookkeeping lost or duplicated keys under "
        "concurrency")
