"""TPL015 negatives: declared events, declared keys, spread fills."""


def emit(log, stats):
    log.append({"event": "ping", "seq": 1, "note": "ok"})
    # a **spread may carry the required keys
    log.append({"event": "pong", **stats})


def consume(events):
    latency = 0.0
    for ev in events:
        if ev.get("event") == "pong":
            latency += ev.get("latency") or 0.0
            continue
        if ev.get("event") != "ping":
            continue
        # consumer-local annotations (leading underscore) are exempt
        ev["_stream"] = "s"
        _ = ev["seq"], ev.get("note")
    return latency
