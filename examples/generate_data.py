"""Generate the example datasets (label-first CSVs, reference layout).

The reference ships binary/regression/lambdarank/multiclass example
data files (examples/*/ *.train, *.test); this repo generates
equivalent synthetic sets instead of copying them. Deterministic:
seeded, so re-running reproduces byte-identical files.

Usage:  python examples/generate_data.py [outdir]
"""

import os
import sys

import numpy as np


def _write(path, y, X, fmt="%.6g"):
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt=fmt)


def binary(d, n=7000, f=28, seed=1):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    logits = X[:, :8] @ rs.randn(8) + 0.4 * rs.randn(n)
    y = (logits > 0).astype(float)
    cut = int(n * 0.85)
    _write(os.path.join(d, "binary.train"), y[:cut], X[:cut])
    _write(os.path.join(d, "binary.test"), y[cut:], X[cut:])


def regression(d, n=7000, f=20, seed=2):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = X[:, :5] @ rs.randn(5) + 0.3 * rs.randn(n)
    cut = int(n * 0.85)
    _write(os.path.join(d, "regression.train"), y[:cut], X[:cut])
    _write(os.path.join(d, "regression.test"), y[cut:], X[cut:])


def multiclass(d, n=6000, f=12, k=5, seed=3):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    centers = rs.randn(k, f) * 1.5
    y = np.argmin(
        ((X[:, None, :] - centers[None]) ** 2).sum(-1), axis=1
    ).astype(float)
    cut = int(n * 0.85)
    _write(os.path.join(d, "multiclass.train"), y[:cut], X[:cut])
    _write(os.path.join(d, "multiclass.test"), y[cut:], X[cut:])


def lambdarank(d, n_query=300, per_q=15, f=10, seed=4):
    rs = np.random.RandomState(seed)
    n = n_query * per_q
    X = rs.randn(n, f)
    rel = X[:, 0] + 0.5 * X[:, 3] + 0.4 * rs.randn(n)
    # graded relevance 0-4 per query by within-query rank
    y = np.zeros(n)
    for q in range(n_query):
        s = slice(q * per_q, (q + 1) * per_q)
        order = np.argsort(-rel[s])
        grades = np.zeros(per_q)
        grades[order[:2]] = [4, 3]
        grades[order[2:5]] = 2
        grades[order[5:8]] = 1
        y[s] = grades
    cut_q = int(n_query * 0.85)
    cut = cut_q * per_q
    _write(os.path.join(d, "rank.train"), y[:cut], X[:cut])
    _write(os.path.join(d, "rank.test"), y[cut:], X[cut:])
    np.savetxt(os.path.join(d, "rank.train.query"),
               np.full(cut_q, per_q, np.int64), fmt="%d")
    np.savetxt(os.path.join(d, "rank.test.query"),
               np.full(n_query - cut_q, per_q, np.int64), fmt="%d")


GENERATORS = {
    "binary_classification": binary,
    "regression": regression,
    "multiclass_classification": multiclass,
    "lambdarank": lambdarank,
}


def main(base=None):
    base = base or os.path.dirname(os.path.abspath(__file__))
    for name, gen in GENERATORS.items():
        d = os.path.join(base, name)
        os.makedirs(d, exist_ok=True)
        gen(d)
        print(f"generated {name}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
