"""Utilities."""
