"""Host-side feature binning (quantile sketch).

TPU-native re-design of the reference BinMapper
(/root/reference/src/io/bin.cpp: GreedyFindBin :78, FindBinWithZeroAsOneBin
:242, BinMapper::FindBin :311; include/LightGBM/bin.h:85-260).

Binning runs once on the host (numpy, vectorized) at Dataset construction;
its product is a dense ``[num_rows, num_features]`` uint8/uint16 bin matrix
that lives in HBM for the whole training run (the CUDARowData analog,
SURVEY.md §2.8). Unlike the reference there is no per-bin most-frequent-bin
omission in histograms — on TPU we always accumulate every bin, so the
``FixHistogram`` reconstruction step does not exist.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BinMapper", "BinType", "MissingType", "find_bin", "bin_values"]

# Matches the reference's kZeroThreshold (bin.h): |v| <= kZero is "zero".
K_ZERO_THRESHOLD = 1e-35
K_SPARSE_THRESHOLD = 0.8


class BinType:
    NUMERICAL = "numerical"
    CATEGORICAL = "categorical"


class MissingType:
    NONE = "none"
    ZERO = "zero"
    NAN = "nan"


@dataclasses.dataclass
class BinMapper:
    """Per-feature value->bin mapping."""

    bin_type: str = BinType.NUMERICAL
    missing_type: str = MissingType.NONE
    num_bins: int = 1
    # numerical: ascending upper bounds, one per bin (last = +inf).
    upper_bounds: Optional[np.ndarray] = None
    # categorical: category value for each bin index.
    bin_to_cat: Optional[np.ndarray] = None
    cat_to_bin: Optional[Dict[int, int]] = None
    default_bin: int = 0       # the bin containing 0.0
    most_freq_bin: int = 0
    sparse_rate: float = 0.0
    min_value: float = 0.0
    max_value: float = 0.0

    @property
    def is_trivial(self) -> bool:
        return self.num_bins <= 1

    # -- mapping ---------------------------------------------------------
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (the ValueToBin analog, bin.h:193).

        Pass-count matters: this maps every cell of the training matrix
        (4228 columns at Allstate width), so NaN handling is gated on
        the mapper's missing_type instead of paying isnan+where passes
        on clean columns, and the searchsorted result is clamped/cast
        in one pass."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BinType.CATEGORICAL:
            out = np.zeros(values.shape, dtype=np.int32)
            iv = np.where(np.isfinite(values), values, -1).astype(np.int64)
            for cat, b in (self.cat_to_bin or {}).items():
                out[iv == cat] = b
            return out
        if self.missing_type == MissingType.NAN:
            nan_mask = np.isnan(values)
            bins = np.searchsorted(self.upper_bounds, values, side="left")
            np.minimum(bins, len(self.upper_bounds) - 1, out=bins)
            bins = np.where(nan_mask, self.num_bins - 1, bins)
            return bins.astype(np.int32)
        # MissingType.NONE/ZERO: NaN cells map to the bin of 0.0 (the
        # where -> searchsorted(0.0) below; the native kernel hardcodes
        # the same via nan_to). A clean column pays one isnan read pass
        # but skips the where copy.
        nan_mask = np.isnan(values)
        if nan_mask.any():
            values = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(self.upper_bounds, values, side="left")
        np.minimum(bins, len(self.upper_bounds) - 1, out=bins)
        return bins.astype(np.int32)

    def bin_to_value(self, b: int) -> float:
        """Representative value of a bin (used for threshold realization)."""
        if self.bin_type == BinType.CATEGORICAL:
            return float(self.bin_to_cat[b]) if b < len(self.bin_to_cat) else 0.0
        return float(self.upper_bounds[min(b, len(self.upper_bounds) - 1)])

    def bin_upper_bound(self, b: int) -> float:
        """Real-valued split threshold for 'bin <= b'."""
        if b >= len(self.upper_bounds):
            return float("inf")
        return float(self.upper_bounds[b])

    def to_dict(self) -> dict:
        return {
            "bin_type": self.bin_type,
            "missing_type": self.missing_type,
            "num_bins": int(self.num_bins),
            "upper_bounds": None if self.upper_bounds is None
            else [float(x) for x in self.upper_bounds],
            "bin_to_cat": None if self.bin_to_cat is None
            else [int(x) for x in self.bin_to_cat],
            "default_bin": int(self.default_bin),
            "most_freq_bin": int(self.most_freq_bin),
            "sparse_rate": float(self.sparse_rate),
            "min_value": float(self.min_value),
            "max_value": float(self.max_value),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls(
            bin_type=d["bin_type"],
            missing_type=d["missing_type"],
            num_bins=d["num_bins"],
            upper_bounds=None if d.get("upper_bounds") is None
            else np.asarray(d["upper_bounds"], dtype=np.float64),
            bin_to_cat=None if d.get("bin_to_cat") is None
            else np.asarray(d["bin_to_cat"], dtype=np.int64),
            default_bin=d.get("default_bin", 0),
            most_freq_bin=d.get("most_freq_bin", 0),
            sparse_rate=d.get("sparse_rate", 0.0),
            min_value=d.get("min_value", 0.0),
            max_value=d.get("max_value", 0.0),
        )
        if m.bin_to_cat is not None:
            m.cat_to_bin = {int(c): i for i, c in enumerate(m.bin_to_cat)}
        return m


def _greedy_find_bin(distinct: np.ndarray, counts: np.ndarray,
                     num_distinct: int, max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Equal-count greedy bin boundaries over sorted distinct values.

    Semantics follow the reference's GreedyFindBin (bin.cpp:78): when few
    distinct values, one bin per value (merging tiny bins per
    min_data_in_bin); otherwise greedy equal-count packing where any value
    holding >= mean-bin-size data is pinned to its own bin.
    Returns upper bounds; the caller appends/uses +inf as the last bound.
    """
    bounds: List[float] = []
    if num_distinct == 0:
        return bounds
    distinct = distinct[:num_distinct]
    counts = counts[:num_distinct]
    if num_distinct <= max_bin:
        cur = 0
        for i in range(num_distinct - 1):
            cur += counts[i]
            if cur >= min_data_in_bin:
                bounds.append((distinct[i] + distinct[i + 1]) / 2.0)
                cur = 0
        bounds.append(float("inf"))
        return bounds

    max_bin = max(1, max_bin)
    mean_bin_size = total_cnt / max_bin
    # values that alone exceed the mean bin size get private bins
    is_big = counts >= mean_bin_size
    rest_cnt = total_cnt - counts[is_big].sum()
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_bin_size = rest_cnt / rest_bins

    bin_cnt = 0
    cur = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_cnt -= counts[i]
        cur += counts[i]
        # close the current bin if: value is big, bin is full, or the next
        # value is big (so it must start its own bin)
        if (is_big[i] or cur >= mean_bin_size or
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            bounds.append((distinct[i] + distinct[i + 1]) / 2.0)
            bin_cnt += 1
            cur = 0
            if bin_cnt >= max_bin - 1:
                break
            if not is_big[i] and rest_bins > bin_cnt:
                # re-balance remaining budget over remaining small values
                remaining_small_bins = rest_bins - (
                    bin_cnt - int(is_big[: i + 1].sum()))
                if remaining_small_bins > 0:
                    mean_bin_size = rest_cnt / remaining_small_bins
    bounds.append(float("inf"))
    return bounds


def _find_bounds_zero_as_one_bin(values: np.ndarray, max_bin: int,
                                 min_data_in_bin: int,
                                 total_sample_cnt: int) -> List[float]:
    """Numerical bounds where zero always occupies its own bin
    (FindBinWithZeroAsOneBin analog, bin.cpp:242)."""
    left = values[values < -K_ZERO_THRESHOLD]
    right = values[values > K_ZERO_THRESHOLD]
    left_cnt, right_cnt = len(left), len(right)
    non_zero = left_cnt + right_cnt
    zero_cnt = max(0, total_sample_cnt - non_zero)

    bounds: List[float] = []
    eff = max(1, non_zero + zero_cnt)
    left_max_bin = 0
    if left_cnt > 0:
        left_max_bin = max(1, int(round((max_bin - 1) * left_cnt / eff)))
        dl, cl = np.unique(left, return_counts=True)
        lb = _greedy_find_bin(dl, cl, len(dl), left_max_bin, left_cnt,
                              min_data_in_bin)
        if lb:
            lb[-1] = -K_ZERO_THRESHOLD
        bounds.extend(lb)
    if right_cnt > 0 or zero_cnt > 0:
        bounds.append(K_ZERO_THRESHOLD)
    if right_cnt > 0:
        right_max_bin = max_bin - 1 - len(bounds) + 1
        right_max_bin = max(1, right_max_bin)
        dr, cr = np.unique(right, return_counts=True)
        rb = _greedy_find_bin(dr, cr, len(dr), right_max_bin, right_cnt,
                              min_data_in_bin)
        bounds.extend(rb)
    if not bounds or bounds[-1] != float("inf"):
        bounds.append(float("inf"))
    # dedupe while preserving order (zero bounds can collide on tiny data)
    out: List[float] = []
    for b in bounds:
        if not out or b > out[-1]:
            out.append(b)
    return out


def find_bin(values: np.ndarray,
             max_bin: int,
             min_data_in_bin: int = 3,
             bin_type: str = BinType.NUMERICAL,
             use_missing: bool = True,
             zero_as_missing: bool = False,
             total_sample_cnt: Optional[int] = None,
             min_data_per_group: int = 100,
             max_cat: int = 0x7FFFFFFF) -> BinMapper:
    """Build a BinMapper for one feature from (a sample of) its values.

    ``values`` may contain NaN. ``total_sample_cnt`` can exceed
    ``len(values)`` when sparse rows were skipped — the difference is
    treated as implicit zeros (matching BinMapper::FindBin, bin.cpp:311).
    """
    values = np.asarray(values, dtype=np.float64).ravel()
    if total_sample_cnt is None:
        total_sample_cnt = len(values)
    nan_mask = np.isnan(values)
    na_cnt = int(nan_mask.sum())
    finite = values[~nan_mask]

    if bin_type == BinType.CATEGORICAL:
        return _find_bin_categorical(finite, max_bin, na_cnt, use_missing,
                                     total_sample_cnt, min_data_in_bin)

    # missing policy (BinMapper::FindBin missing-type selection)
    if not use_missing:
        missing_type = MissingType.NONE
    elif zero_as_missing:
        missing_type = MissingType.ZERO
    elif na_cnt > 0:
        missing_type = MissingType.NAN
    else:
        missing_type = MissingType.NONE

    if missing_type == MissingType.NONE and na_cnt > 0:
        # NaN folded into zero when missing handling disabled
        finite = np.concatenate([finite, np.zeros(na_cnt)])
        na_cnt = 0

    budget = max_bin - 1 if missing_type == MissingType.NAN else max_bin
    budget = max(budget, 1)
    n_total_for_bounds = total_sample_cnt - na_cnt
    bounds = _find_bounds_zero_as_one_bin(
        finite, budget, min_data_in_bin, n_total_for_bounds)
    upper = np.asarray(bounds, dtype=np.float64)
    num_bins = len(upper) + (1 if missing_type == MissingType.NAN else 0)

    m = BinMapper(
        bin_type=BinType.NUMERICAL,
        missing_type=missing_type,
        num_bins=int(num_bins),
        upper_bounds=upper,
        min_value=float(finite.min()) if len(finite) else 0.0,
        max_value=float(finite.max()) if len(finite) else 0.0,
    )
    m.default_bin = int(np.searchsorted(upper, 0.0, side="left"))
    # most_freq_bin from the sample histogram (incl. implicit zeros)
    if len(finite) or total_sample_cnt > 0:
        bin_ids = np.searchsorted(upper, finite, side="left")
        bin_ids = np.minimum(bin_ids, len(upper) - 1)
        cnt = np.bincount(bin_ids, minlength=num_bins).astype(np.int64)
        cnt[m.default_bin] += total_sample_cnt - na_cnt - len(finite)
        if missing_type == MissingType.NAN:
            cnt[num_bins - 1] += na_cnt
        m.most_freq_bin = int(cnt.argmax())
        m.sparse_rate = float(cnt[m.default_bin]) / max(1, total_sample_cnt)
    return m


def _find_bin_categorical(finite: np.ndarray, max_bin: int, na_cnt: int,
                          use_missing: bool, total_sample_cnt: int,
                          min_data_in_bin: int) -> BinMapper:
    iv = finite.astype(np.int64)
    if (iv < 0).any():
        import warnings
        warnings.warn("Met negative categorical value, converted to NaN",
                      stacklevel=3)
        na_cnt += int((iv < 0).sum())
        iv = iv[iv >= 0]
    cats, counts = np.unique(iv, return_counts=True)
    order = np.argsort(-counts, kind="stable")
    cats, counts = cats[order], counts[order]
    # keep categories covering 99% of data, capped at max_bin-1 bins
    # (bin 0 additionally absorbs unseen values)
    cut = int(len(cats))
    if len(cats) > max_bin - 1:
        cut = max_bin - 1
    total = counts.sum()
    if total > 0 and len(cats) > 2:
        cum = np.cumsum(counts)
        cut99 = int(np.searchsorted(cum, 0.99 * total) + 1)
        cut = min(cut, max(cut99, 1))
    cats, counts = cats[:cut], counts[:cut]
    missing_type = MissingType.NAN if (use_missing and na_cnt > 0) \
        else MissingType.NONE
    m = BinMapper(
        bin_type=BinType.CATEGORICAL,
        missing_type=missing_type,
        num_bins=int(len(cats)) if len(cats) else 1,
        bin_to_cat=cats.copy(),
        cat_to_bin={int(c): i for i, c in enumerate(cats)},
        most_freq_bin=0,
    )
    if len(counts):
        m.sparse_rate = 1.0 - counts.sum() / max(1, total_sample_cnt)
    return m


def bin_values(columns: Sequence[np.ndarray], mappers: Sequence[BinMapper],
               dtype=None) -> np.ndarray:
    """Bin a list of feature columns into a dense [n, F] matrix."""
    n = len(columns[0]) if columns else 0
    max_bins = max((m.num_bins for m in mappers), default=2)
    if dtype is None:
        dtype = np.uint8 if max_bins <= 256 else np.uint16
    out = np.zeros((n, len(columns)), dtype=dtype)
    for j, (col, m) in enumerate(zip(columns, mappers)):
        out[:, j] = m.value_to_bin(col).astype(dtype)
    return out


def bin_matrix(X: np.ndarray, col_indices, mappers: Sequence[BinMapper],
               dtype=None) -> np.ndarray:
    """Bin selected columns of a row-major [n, F] values matrix into a
    dense [n, C] bin matrix.

    Numerical columns go through the native C++ kernel when available
    (utils/native.py ltpu_bin_columns — the reference also bins with
    compiled code, bin.h ValueToBin): the numpy per-column path costs
    ~100-160 ns/value in call dispatch and strided access, which at
    Allstate width (4228 columns) made construct the wall-clock
    bottleneck (benchmarks/PROFILE.md round 5). Categorical columns
    (dict lookups) and unsupported dtypes fall back to value_to_bin;
    results are bit-identical either way."""
    col_indices = np.asarray(col_indices, np.int64)
    max_bins = max((m.num_bins for m in mappers), default=2)
    if dtype is None:
        dtype = np.uint8 if max_bins <= 256 else np.uint16
    n = X.shape[0]
    num_sel = [i for i, m in enumerate(mappers)
               if m.bin_type == BinType.NUMERICAL]
    sub = None
    if num_sel and isinstance(X, np.ndarray) \
            and X.dtype in (np.float32, np.float64) \
            and X.flags.c_contiguous:
        from ..utils.native import bin_columns_native
        bounds_list = [mappers[i].upper_bounds for i in num_sel]
        nan_to = np.asarray(
            [mappers[i].num_bins - 1
             if mappers[i].missing_type == MissingType.NAN
             else min(int(np.searchsorted(mappers[i].upper_bounds, 0.0,
                                          side="left")),
                      len(mappers[i].upper_bounds) - 1)
             for i in num_sel], np.int32)
        sub = bin_columns_native(
            X, col_indices[num_sel].astype(np.int32), bounds_list,
            nan_to, dtype)
    if sub is not None and len(num_sel) == len(mappers):
        return sub
    out = np.zeros((n, len(mappers)), dtype=dtype)
    if sub is not None:
        out[:, num_sel] = sub
        sel = set(num_sel)
        rest = [i for i in range(len(mappers)) if i not in sel]
    else:
        rest = range(len(mappers))
    for i in rest:
        out[:, i] = mappers[i].value_to_bin(
            X[:, col_indices[i]]).astype(dtype)
    return out
