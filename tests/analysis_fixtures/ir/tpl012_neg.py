"""TPL012 negative: the same psum as tpl012_pos with a budget that
matches the measured payload exactly — measured <= committed on every
metric, so no finding."""


def build(jax, jnp):
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.parallel.data_parallel import shard_map
    from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh
    mesh = make_mesh(8, devices=jax.devices("cpu"))
    fn = shard_map(lambda x: jax.lax.psum(x, DATA_AXIS), mesh,
                   in_specs=P(DATA_AXIS), out_specs=P(),
                   check_rep=False)
    return fn, (jnp.ones((8, 32), jnp.float32),)


BUDGET = {"n_collectives": 1, "wire_bytes": 128,
          "post_reduction_bytes": 128,
          "justification": "one (1, 32) f32 psum operand each way"}
