"""Conformance runs over the reference's example datasets
(/root/reference/examples/*, the test_consistency.py:143 pattern):
train with each example's train.conf settings through the CLI config
parser and assert the learned model reaches reference-grade quality on
the example's own validation file."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import load_config_file as parse_config_file

REF = "/root/reference/examples"

pytestmark = pytest.mark.skipif(not os.path.isdir(REF),
                                reason="reference examples not mounted")


def _load_conf(example, name="train.conf"):
    return parse_config_file(os.path.join(REF, example, name))


def _params_from_conf(conf, drop=("task", "data", "valid_data",
                                  "output_model", "num_machines",
                                  "local_listen_port",
                                  "machine_list_file", "is_pre_partition",
                                  "use_two_round_loading",
                                  "is_save_binary_file", "num_trees",
                                  "is_training_metric", "metric_freq",
                                  "label_column")):
    params = {k: v for k, v in conf.items() if k not in drop}
    params["verbosity"] = -1
    return params


def _auc(y, p):
    order = np.argsort(p)
    rank = np.empty(len(p))
    rank[order] = np.arange(1, len(p) + 1)
    npos = y.sum()
    nneg = len(y) - npos
    return (rank[y > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def _load_libsvm(path, nf):
    """LibSVM rows -> dense [n, nf] + labels (0-based indices, the
    reference parser's convention)."""
    labels, rows = [], []
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            labels.append(float(parts[0]))
            row = np.zeros(nf)
            for tok in parts[1:]:
                i, v = tok.split(":")
                if int(i) < nf:
                    row[int(i)] = float(v)
            rows.append(row)
    return np.asarray(rows), np.asarray(labels)


def _ndcg_at(y, p, qs, k):
    total, cnt, off = 0.0, 0, 0
    for q in qs:
        yy, pp = y[off:off + q], p[off:off + q]
        off += q
        if yy.max() <= 0:
            continue
        top = np.argsort(-pp)[:k]
        dcg = np.sum((2.0 ** yy[top] - 1)
                     / np.log2(np.arange(2, len(top) + 2)))
        ideal = np.sort(yy)[::-1][:k]
        idcg = np.sum((2.0 ** ideal - 1)
                      / np.log2(np.arange(2, len(ideal) + 2)))
        total += dcg / idcg
        cnt += 1
    return total / max(cnt, 1)


def test_binary_classification_example():
    conf = _load_conf("binary_classification")
    base = os.path.join(REF, "binary_classification")
    train = lgb.Dataset(os.path.join(base, conf["data"]),
                        params={"max_bin": int(conf["max_bin"]),
                                "label_column": conf["label_column"]})
    params = _params_from_conf(conf)
    bst = lgb.train(params, train, num_boost_round=50)
    test = np.loadtxt(os.path.join(base, "binary.test"))
    y, X = test[:, 0], test[:, 1:]
    p = bst.predict(X)
    auc = _auc(y, p)
    # the reference CLI run reaches ~0.78 held-out AUC on this example
    # at 50 iterations; conformance = same ballpark, not bitwise
    assert auc > 0.75, auc
    ll = -np.mean(y * np.log(np.clip(p, 1e-12, 1))
                  + (1 - y) * np.log(np.clip(1 - p, 1e-12, 1)))
    assert ll < 0.60, ll


def test_lambdarank_example():
    conf = _load_conf("lambdarank")
    base = os.path.join(REF, "lambdarank")
    train = lgb.Dataset(os.path.join(base, conf["data"]),
                        params={"max_bin": int(conf["max_bin"]),
                                "label_column": conf["label_column"]})
    params = _params_from_conf(conf)
    bst = lgb.train(params, train, num_boost_round=50)

    # rank.test is LibSVM-formatted (label idx:value ...)
    X, y = _load_libsvm(os.path.join(base, "rank.test"),
                        bst.num_feature())
    qs = np.loadtxt(os.path.join(base, "rank.test.query")).astype(int)
    p = bst.predict(X)
    # calibration on this dataset: random ranking scores ndcg@5 ~0.47;
    # the trained model must sit well above it
    assert _ndcg_at(y, p, qs, 5) > 0.60, _ndcg_at(y, p, qs, 5)


def test_multiclass_example():
    base = os.path.join(REF, "multiclass_classification")
    conf = _load_conf("multiclass_classification")
    dparams = {"label_column": conf.get("label_column", "0")}
    train = lgb.Dataset(os.path.join(base, conf["data"]), params=dparams)
    valid = lgb.Dataset(os.path.join(base, conf["valid_data"]),
                        params=dparams, reference=train)
    params = _params_from_conf(conf)
    # the conf sets early_stopping = 10, exercised against valid_data
    # the conf sets num_trees=100 with early_stopping=10
    bst = lgb.train(params, train, num_boost_round=100,
                    valid_sets=[valid])
    test = np.loadtxt(os.path.join(base, "multiclass.test"))
    y, X = test[:, 0].astype(int), test[:, 1:]
    p = bst.predict(X)  # [n, K]
    err = np.mean(np.argmax(p, axis=1) != y)
    # calibration: random guessing errs 0.8; sklearn
    # HistGradientBoosting errs 0.484 on this (hard, tiny) test split
    assert err < 0.58, err


def test_model_txt_loads_and_round_trips(tmp_path):
    """A saved model.txt from the binary example reloads bit-exactly and
    its text structure carries the reference format markers."""
    base = os.path.join(REF, "binary_classification")
    conf = _load_conf("binary_classification")
    train = lgb.Dataset(os.path.join(base, conf["data"]),
                        params={"max_bin": int(conf["max_bin"]),
                                "label_column": conf["label_column"]})
    bst = lgb.train(_params_from_conf(conf), train, num_boost_round=5)
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    text = path.read_text()
    for marker in ("tree", "num_leaves=", "split_feature=",
                   "objective=binary", "feature_names",
                   "end of trees"):
        assert marker in text, marker
    test = np.loadtxt(os.path.join(base, "binary.test"))
    X = test[:, 1:]
    p1 = bst.predict(X)
    p2 = lgb.Booster(model_file=str(path)).predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-9)


# ---------------------------------------------------------------------------
# Cross-implementation model exchange: models PRODUCED BY THE REFERENCE
# CLI (tests/data/README.md documents provenance) must load and predict
# here. Per-row agreement is f32-boundary-limited: device prediction
# compares f32 values against f32-rounded thresholds, so rows whose
# f64 feature value sits between a threshold and its f32 rounding can
# route differently (6/500 rows on the binary example); everything
# else matches the reference's own predictions to float precision.
# ---------------------------------------------------------------------------

_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def test_reference_binary_model_cross_loads():
    bst = lgb.Booster(model_file=os.path.join(_DATA, "binary.model.txt"))
    assert bst.num_trees() == 100
    X = np.loadtxt(os.path.join(REF, "binary_classification",
                                "binary.test"), delimiter="\t")[:, 1:]
    p = bst.predict(X)
    ref = np.loadtxt(os.path.join(_DATA, "binary.pred.txt"))
    d = np.abs(p - ref)
    assert np.median(d) < 1e-7
    assert np.mean(d < 1e-6) >= 0.98
    assert d.max() < 0.05
    # quality identical on the example's own labels
    y = np.loadtxt(os.path.join(REF, "binary_classification",
                                "binary.test"), delimiter="\t")[:, 0]
    acc_ours = np.mean((p > 0.5) == (y > 0.5))
    acc_ref = np.mean((ref > 0.5) == (y > 0.5))
    assert abs(acc_ours - acc_ref) <= 0.004


def test_reference_ranker_model_cross_loads():
    from lightgbm_tpu.basic import _load_text_file
    from lightgbm_tpu.config import Config
    bst = lgb.Booster(model_file=os.path.join(_DATA, "rank.model.txt"))
    assert bst.num_trees() == 100
    # parse rank.test with OUR LibSVM parser (reference-equivalent
    # 0-based indexing; sklearn's loader re-bases indices)
    X, _, _, _ = _load_text_file(os.path.join(REF, "lambdarank",
                                              "rank.test"), Config())
    nf = bst.num_feature()
    if X.shape[1] < nf:
        X = np.hstack([X, np.zeros((X.shape[0], nf - X.shape[1]))])
    p = bst.predict(X[:, :nf])
    ref = np.loadtxt(os.path.join(_DATA, "rank.pred.txt"))
    d = np.abs(p - ref)
    assert np.median(d) < 1e-6
    assert d.max() < 1e-4


def test_reference_regression_model_cross_loads():
    bst = lgb.Booster(model_file=os.path.join(_DATA,
                                              "regression.model.txt"))
    assert bst.num_trees() == 100
    X = np.loadtxt(os.path.join(REF, "regression", "regression.test"),
                   delimiter="\t")[:, 1:]
    p = bst.predict(X)
    ref = np.loadtxt(os.path.join(_DATA, "regression.pred.txt"))
    d = np.abs(p - ref)
    assert np.median(d) < 1e-6
    assert np.mean(d < 1e-5) >= 0.98


def test_reference_multiclass_model_cross_loads():
    bst = lgb.Booster(model_file=os.path.join(_DATA,
                                              "multiclass.model.txt"))
    assert bst.num_trees() == 500  # 100 iters x 5 classes
    X = np.loadtxt(os.path.join(REF, "multiclass_classification",
                                "multiclass.test"), delimiter="\t")[:, 1:]
    p = bst.predict(X)
    ref = np.loadtxt(os.path.join(_DATA, "multiclass.pred.txt"))
    assert p.shape == ref.shape
    d = np.abs(p - ref)
    assert np.median(d) < 1e-6
    # softmax couples classes: one f32-boundary-flipped tree perturbs
    # all 5 class probabilities of that row
    assert np.mean(d < 1e-4) >= 0.95
    assert d.max() < 0.05


def test_xendcg_example():
    """The xendcg example (objective=rank_xendcg) trains to a ranking
    quality well above random on its own validation queries — the same
    bar the lambdarank example is held to."""
    conf = _load_conf("xendcg")
    base = os.path.join(REF, "xendcg")
    train = lgb.Dataset(os.path.join(base, conf["data"]),
                        params={"label_column":
                                conf.get("label_column", "0")})
    params = _params_from_conf(conf)
    bst = lgb.train(params, train, num_boost_round=50)

    X, y = _load_libsvm(os.path.join(base, "rank.test"),
                        bst.num_feature())
    qs = np.loadtxt(os.path.join(base, "rank.test.query")).astype(int)
    p = bst.predict(X)
    assert _ndcg_at(y, p, qs, 5) > 0.60, _ndcg_at(y, p, qs, 5)
