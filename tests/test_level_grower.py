"""Depth-wise level grower (ops/grow.py _grow_level_impl).

The level grower fuses each frontier level's histogram -> best-split ->
partition chain into one loop iteration of a single traced program.
Depth-wise and leaf-wise growth are DIFFERENT policies whenever the
leaf budget binds mid-frontier, so the equivalence oracle is the
regime where they provably coincide: a depth cap with a non-binding
budget, where both policies split exactly the leaves with positive
gain. Everything else is semantic invariants (budget/depth caps,
partition consistency, gating) plus engine-level training.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.grow import GrowConfig, grow_tree


def _mk(n=6000, F=6, B=31, seed=1, weights=None, cat=False):
    rs = np.random.RandomState(seed)
    bins = jnp.asarray(rs.randint(0, B, (F, n)).astype(np.uint8))
    g = jnp.asarray(rs.randn(n).astype(np.float32))
    h = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))
    w = jnp.ones((n,), jnp.float32) if weights is None \
        else jnp.asarray(weights.astype(np.float32))
    fic = None
    if cat:
        fic = jnp.asarray(np.arange(F) % 3 == 0)
    return (bins, g, h, w, jnp.ones((F,), bool),
            jnp.full((F,), B, jnp.int32),
            jnp.full((F,), -1, jnp.int32)), fic


def _preds(t, rl):
    return np.asarray(t.leaf_value)[np.asarray(rl)]


@pytest.mark.parametrize("m", ["scatter", "mxu", "pallas"])
def test_matches_leafwise_under_depth_cap(m):
    """Non-binding budget + depth cap: both policies split the same
    leaf set, so row partitions and per-row outputs agree (node
    numbering is creation-order and differs by design)."""
    args, _ = _mk()
    cfgL = GrowConfig(num_leaves=16, num_bins=31, grower="level",
                      hist_method=m, max_depth=4)
    cfgC = GrowConfig(num_leaves=16, num_bins=31, grower="compact",
                      hist_method="scatter", chunk=1024, max_depth=4)
    tL, rlL = grow_tree(cfgL, *args)
    tC, rlC = grow_tree(cfgC, *args)
    assert int(tL.num_leaves) == int(tC.num_leaves)
    np.testing.assert_allclose(_preds(tL, rlL), _preds(tC, rlC),
                               atol=1e-5)


def test_matches_masked_with_bagging_weights():
    """Zero-weight (out-of-bag) rows: counts and sums must track the
    bagged subset exactly, matching the masked oracle."""
    rs = np.random.RandomState(3)
    w = (rs.rand(6000) > 0.4).astype(np.float32) * 1.3
    args, _ = _mk(weights=w)
    cfgL = GrowConfig(num_leaves=8, num_bins=31, grower="level",
                      hist_method="scatter", max_depth=3)
    cfgM = GrowConfig(num_leaves=8, num_bins=31, grower="masked",
                      hist_method="scatter", max_depth=3)
    tL, rlL = grow_tree(cfgL, *args)
    tM, rlM = grow_tree(cfgM, *args)
    assert int(tL.num_leaves) == int(tM.num_leaves)
    np.testing.assert_allclose(_preds(tL, rlL), _preds(tM, rlM),
                               atol=1e-5)
    nl = int(tL.num_leaves)
    np.testing.assert_allclose(
        np.sort(np.asarray(tL.leaf_count)[:nl]),
        np.sort(np.asarray(tM.leaf_count)[:nl]), atol=0.5)


def test_categorical_splits_match_leafwise():
    args, fic = _mk(cat=True)
    cfgL = GrowConfig(num_leaves=16, num_bins=31, grower="level",
                      hist_method="scatter", max_depth=4)
    cfgC = GrowConfig(num_leaves=16, num_bins=31, grower="compact",
                      hist_method="scatter", chunk=1024, max_depth=4)
    tL, rlL = grow_tree(cfgL, *args, feat_is_cat=fic)
    tC, rlC = grow_tree(cfgC, *args, feat_is_cat=fic)
    assert int(tL.num_leaves) == int(tC.num_leaves)
    np.testing.assert_allclose(_preds(tL, rlL), _preds(tC, rlC),
                               atol=1e-5)


def test_budget_and_depth_invariants():
    """Binding budget: gain-ranked election keeps leaves <= budget,
    depth <= cap, and the leaf windows partition the rows."""
    args, _ = _mk()
    n = args[0].shape[1]
    cfg = GrowConfig(num_leaves=11, num_bins=31, grower="level",
                     hist_method="scatter")
    t, rl = grow_tree(cfg, *args)
    nl = int(t.num_leaves)
    assert 1 < nl <= 11
    counts = np.asarray(t.leaf_count)[:nl]
    assert counts.sum() == n
    # every row routes to an active leaf, and per-leaf row counts
    # agree with the partition
    rl_np = np.asarray(rl)
    assert rl_np.min() >= 0 and rl_np.max() < nl
    np.testing.assert_array_equal(np.bincount(rl_np, minlength=nl),
                                  counts.astype(np.int64))
    # depth-wise shape: a level-d leaf exists only if level d-1 split,
    # so depth never exceeds the split count and the deepest two
    # levels hold all leaves of a balanced-policy tree
    depths = np.asarray(t.leaf_depth)[:nl]
    assert depths.max() <= nl - 1


def test_unsupported_features_raise():
    args, _ = _mk(n=500)
    cfg = GrowConfig(num_leaves=8, num_bins=31, grower="level",
                     hist_method="scatter", bynode=0.5)
    with pytest.raises(NotImplementedError, match="level"):
        grow_tree(cfg, *args,
                  node_key=None)


def test_engine_trains_and_predicts():
    """lgb.train with grower=level: fused-step eligible, loss
    improves, model round-trips through predict."""
    rs = np.random.RandomState(11)
    X = rs.randn(3000, 8).astype(np.float32)
    y = ((X[:, :4] @ rs.randn(4)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 16,
                     "max_depth": 4, "grower": "level", "max_bin": 63,
                     "verbosity": -1}, ds, num_boost_round=8)
    assert bst._engine.grow_cfg.grower == "level"
    p = bst.predict(X)
    assert p.shape == (3000,)
    # the model separates the synthetic task well above chance
    auc_ok = np.mean((p > 0.5) == (y > 0.5))
    assert auc_ok > 0.8, auc_ok


def test_engine_forces_compact_for_unsupported_configs():
    """Configs outside the level grower's feature set auto-upgrade to
    the compact grower instead of failing (same contract as masked)."""
    rs = np.random.RandomState(12)
    X = rs.randn(800, 6).astype(np.float32)
    y = ((X @ rs.randn(6)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "grower": "level", "max_bin": 31,
                     "use_quantized_grad": True, "verbosity": -1},
                    ds, num_boost_round=2)
    assert bst._engine.grow_cfg.grower == "compact"


def test_config_validates_grower():
    from lightgbm_tpu.config import Config
    assert Config(grower="level").grower == "level"
    with pytest.raises(ValueError, match="grower"):
        Config(grower="depthwise")
