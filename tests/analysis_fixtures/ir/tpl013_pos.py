"""TPL013 positive: ``donate_argnums`` declared on a jit whose output
shape differs from the donated input — XLA cannot alias the buffers,
so the lowered StableHLO carries zero ``tf.aliasing_output`` markers
and the declared donation is silently dead. The finding anchors at the
DONATE line (the contract under review)."""


def build(jax, jnp):
    fn = jax.jit(lambda x: jnp.concatenate([x, x]), donate_argnums=(0,))
    return fn, (jnp.ones((8,), jnp.float32),)


# EXPECT: TPL013
DONATE = (0,)
