"""TPL014 negative: the same entry point WITH a ``max_signatures``
declaration — the recompile surface is committed, so no finding."""


def _identity(x):
    return x


def register_jit(name, fn, max_signatures=None):
    return fn


F = register_jit("fixture/declared", _identity, max_signatures=4)
