"""On-chip serve bench (ROADMAP 3d): rows/s + p99 through the REAL
serving stack — CompiledForest + MicroBatcher, in process, no sockets
— so the number measures model dispatch + micro-batching, not TCP.

Concurrent client threads submit fixed-size row blocks through
``MicroBatcher.submit`` for a fixed wall window; the bench reports
sustained rows/s, request latency percentiles and the batcher's own
coalescing stats as ONE JSON line on stdout (the bench.py contract,
greppable from revive_and_measure.sh). A second traced window samples
requests through the tracing plane (obs/trace.py) and reports the
span-derived stage decomposition — queue wait / batch window / device
dispatch — so an on-chip p99 regression localizes to a stage without
a separate profiling run.

Knobs: BENCH_SERVE_SECS (window, default 10), BENCH_SERVE_CLIENTS
(default 8), BENCH_SERVE_ROWS (rows/request, default 64),
BENCH_SERVE_TREES (default 200), BENCH_SERVE_WINDOW_MS (default 2).

Run:  python benchmarks/serve_bench.py
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.trace import drain_span_events
from lightgbm_tpu.serve.batcher import MicroBatcher
from lightgbm_tpu.serve.compile import compile_forest

SECS = float(os.environ.get("BENCH_SERVE_SECS", "10"))
CLIENTS = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
ROWS = int(os.environ.get("BENCH_SERVE_ROWS", "64"))
TREES = int(os.environ.get("BENCH_SERVE_TREES", "200"))
WINDOW_MS = float(os.environ.get("BENCH_SERVE_WINDOW_MS", "2"))
F = 28


def _train_forest():
    rs = np.random.RandomState(0)
    X = rs.randn(20000, F).astype(np.float32)
    y = ((X @ rs.randn(F)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "max_bin": 63, "verbosity": -1}, ds,
                    num_boost_round=TREES)
    return compile_forest(bst, max_batch_rows=4096)


def _client_loop(batcher, X, stop, lat, errs):
    while not stop.is_set():
        t0 = time.perf_counter()
        try:
            batcher.submit(X).result(timeout=30)
        except Exception:
            errs.append(1)
            continue
        lat.append(time.perf_counter() - t0)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def main():
    t0 = time.perf_counter()
    forest = _train_forest()
    forest.warmup()
    build_s = time.perf_counter() - t0
    batcher = MicroBatcher(forest, batch_window_ms=WINDOW_MS)
    X = np.random.RandomState(1).randn(ROWS, F).astype(np.float32)

    # measured window: CLIENTS threads, untraced (production shape)
    stop = threading.Event()
    lat, errs = [], []
    threads = [threading.Thread(target=_client_loop,
                                args=(batcher, X, stop, lat, errs),
                                daemon=True)
               for _ in range(CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(SECS)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    wall = time.perf_counter() - t0

    # traced window: sample the stage decomposition through the span
    # plane itself (serve/queue_wait / batch_window / dispatch)
    drain_span_events()
    stages = {}
    n_traced = 64
    for _ in range(n_traced):
        fut = batcher.submit(X, trace={"trace_id": "b" * 16,
                                       "span_id": "c" * 16})
        t_sub = time.perf_counter()
        fut.result(timeout=30)
        done = time.perf_counter()
        times = getattr(fut, "trace_times", None)
        if times is None:
            continue
        t_submit, t_deq, t_disp, t_done = times
        for key, dur in (("queue_wait", t_deq - t_submit),
                         ("batch_window", t_disp - t_deq),
                         ("dispatch", t_done - t_disp),
                         ("reply", done - t_done)):
            stages.setdefault(key, []).append(dur)
        del t_sub
    drain_span_events()

    stats = batcher.stats()
    batcher.close()
    lat.sort()
    rec = {
        "metric": "serve_rows_per_sec",
        "value": round(len(lat) * ROWS / wall, 1) if lat else None,
        "unit": "rows/s",
        "requests_per_sec": round(len(lat) / wall, 1),
        "clients": CLIENTS, "rows_per_request": ROWS,
        "window_ms": WINDOW_MS, "trees": TREES,
        "latency_ms": {
            "p50": round((_pct(lat, 0.50) or 0) * 1e3, 3),
            "p95": round((_pct(lat, 0.95) or 0) * 1e3, 3),
            "p99": round((_pct(lat, 0.99) or 0) * 1e3, 3),
            "max": round((lat[-1] if lat else 0) * 1e3, 3)},
        "errors": len(errs),
        "batcher": {k: stats.get(k) for k in
                    ("batches_total", "requests_total", "shed_total",
                     "p50_ms", "p99_ms") if k in stats},
        "stage_ms_mean": {
            k: round(sum(v) / len(v) * 1e3, 3)
            for k, v in sorted(stages.items()) if v},
        "build_s": round(build_s, 1),
    }
    print(json.dumps(rec), flush=True)
    return 0 if lat and not errs else 1


if __name__ == "__main__":
    sys.exit(main())
