"""TreeSHAP: vectorized walk vs the single-row oracle, and the additive
(sum of contribs == raw prediction) property the reference guarantees
(PredictContrib, gbdt.cpp:640)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.shap import (_PathElement, _tree_shap_row,
                               _expected_value, predict_contrib)
from conftest import make_synthetic_binary


def _oracle_contrib(booster, X, trees, K):
    n, _ = X.shape
    F = booster.num_feature()
    out = np.zeros((n, (F + 1) * K), np.float64)
    for ti, tree in enumerate(trees):
        k = ti % K
        base = k * (F + 1)
        if tree.num_leaves <= 1:
            out[:, base + F] += float(tree.leaf_value[0])
            continue
        ev = _expected_value(tree)
        for r in range(n):
            phi = np.zeros(F + 1, np.float64)
            _tree_shap_row(tree, X[r], phi, 0, 0, [], 1.0, 1.0, -1)
            phi[F] += ev
            out[r, base: base + F + 1] += phi
    return out


def _fit(params, X, y, rounds=6):
    return lgb.train({"objective": "binary", "num_leaves": 12,
                      "min_data_in_leaf": 5, "verbosity": -1, **params},
                     lgb.Dataset(X, label=y), num_boost_round=rounds)


def test_vectorized_matches_oracle():
    X, y = make_synthetic_binary(n=800, f=6, seed=31)
    bst = _fit({}, X, y)
    probe = X[:40]
    got = predict_contrib(bst, probe, bst._models, 1)
    want = _oracle_contrib(bst, probe, bst._models, 1)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)


def test_vectorized_matches_oracle_with_nan():
    rs = np.random.RandomState(9)
    X, y = make_synthetic_binary(n=900, f=5, seed=17)
    X = X.copy()
    X[rs.rand(*X.shape) < 0.15] = np.nan
    bst = _fit({}, X, y)
    probe = X[:30]
    got = predict_contrib(bst, probe, bst._models, 1)
    want = _oracle_contrib(bst, probe, bst._models, 1)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)


def test_contrib_sums_to_raw_prediction():
    X, y = make_synthetic_binary(n=1000, f=7, seed=3)
    bst = _fit({}, X, y, rounds=10)
    probe = X[:64]
    contrib = bst.predict(probe, pred_contrib=True)
    raw = bst.predict(probe, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-5, atol=1e-6)


def test_contrib_categorical_matches_oracle():
    rs = np.random.RandomState(5)
    n = 1200
    Xc = rs.randint(0, 8, size=(n, 1)).astype(float)
    Xn = rs.randn(n, 3)
    X = np.hstack([Xc, Xn])
    y = ((Xc[:, 0] % 2 == 0) ^ (Xn[:, 0] > 0)).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 12,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "categorical_feature": [0]},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=5)
    probe = X[:25]
    got = predict_contrib(bst, probe, bst._models, 1)
    want = _oracle_contrib(bst, probe, bst._models, 1)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)
