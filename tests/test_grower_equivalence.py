"""Cross-checks between the two growers and the three histogram methods.

The masked grower + scatter histogram is the simple reference
implementation; the compact grower + MXU nibble histogram is the fast
TPU path. They must agree exactly on tree structure (the reference's
cpu-vs-gpu parity tests, tests/python_package_test/test_dual.py, are the
model for this).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.grow import GrowConfig, grow_tree
from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.split import SplitParams


def _mk(n, F, B, seed=0, with_nan_bin=False):
    rs = np.random.RandomState(seed)
    bins = rs.randint(0, B, size=(F, n)).astype(np.uint8)
    g = rs.randn(n).astype(np.float32)
    h = (np.abs(rs.randn(n)) + 0.1).astype(np.float32)
    w = np.ones(n, np.float32)
    fnb = np.full(F, B, np.int32)
    fnan = np.full(F, -1, np.int32)
    if with_nan_bin:
        fnan[::2] = B - 1
    return (jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(w), jnp.ones((F,), bool), jnp.asarray(fnb),
            jnp.asarray(fnan))


@pytest.mark.parametrize("precision", ["default", "high", "highest"])
def test_hist_mxu_matches_scatter(precision):
    rs = np.random.RandomState(3)
    F, n, B = 11, 5000, 67
    bins_T = jnp.asarray(rs.randint(0, B, size=(F, n)).astype(np.uint8))
    g = jnp.asarray(rs.randn(n).astype(np.float32))
    h = jnp.asarray(rs.rand(n).astype(np.float32))
    w = jnp.asarray((rs.rand(n) > 0.3).astype(np.float32) * 1.7)
    mask = jnp.asarray(rs.rand(n) > 0.5)
    a = build_histogram(bins_T, g, h, w, mask, B, "scatter")
    b = build_histogram(bins_T, g, h, w, mask, B, "mxu", precision)
    # single-pass runs bf16 inputs with f32 accumulation — looser bars
    tol = dict(atol=2e-3, rtol=1e-4) if precision != "default" \
        else dict(atol=0.35, rtol=5e-3)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)


def test_hist_mxu_blocked_path():
    """Row counts above ROW_BLOCK exercise the scan accumulation."""
    rs = np.random.RandomState(4)
    F, n, B = 3, 20000, 256
    bins_T = jnp.asarray(rs.randint(0, B, size=(F, n)).astype(np.uint8))
    g = jnp.asarray(rs.randn(n).astype(np.float32))
    h = jnp.asarray(rs.rand(n).astype(np.float32))
    ones = jnp.ones((n,))
    a = build_histogram(bins_T, g, h, ones, ones.astype(bool), B, "scatter")
    b = build_histogram(bins_T, g, h, ones, ones.astype(bool), B, "mxu",
                        "highest")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=4e-3, rtol=1e-4)


@pytest.mark.parametrize("with_nan", [False, True])
def test_compact_grower_matches_masked(with_nan):
    args = _mk(3000, 6, 64, seed=1, with_nan_bin=with_nan)
    cfg_m = GrowConfig(num_leaves=15, num_bins=64,
                       split=SplitParams(min_data_in_leaf=5.0),
                       grower="masked", hist_method="scatter")
    cfg_c = cfg_m._replace(grower="compact")
    tm, rlm = grow_tree(cfg_m, *args)
    tc, rlc = grow_tree(cfg_c, *args)
    assert int(tm.num_leaves) == int(tc.num_leaves)
    for name in ("split_feature", "threshold_bin", "default_left",
                 "left_child", "right_child", "leaf_count", "leaf_parent"):
        np.testing.assert_array_equal(np.asarray(getattr(tm, name)),
                                      np.asarray(getattr(tc, name)),
                                      err_msg=name)
    for name in ("leaf_value", "split_gain", "leaf_weight"):
        np.testing.assert_allclose(np.asarray(getattr(tm, name)),
                                   np.asarray(getattr(tc, name)),
                                   atol=1e-4, rtol=1e-4, err_msg=name)
    np.testing.assert_array_equal(np.asarray(rlm), np.asarray(rlc))


def test_compact_grower_weighted_rows():
    """Bagging-style zero/amplified weights flow through the compact
    partition (weighted counts gate splits; raw rows stay in ranges)."""
    (bins, g, h, _, fm, fnb, fnan) = _mk(4000, 5, 32, seed=2)
    rs = np.random.RandomState(9)
    w = jnp.asarray((rs.rand(4000) > 0.4).astype(np.float32) * 1.5)
    cfg_m = GrowConfig(num_leaves=10, num_bins=32,
                       split=SplitParams(min_data_in_leaf=5.0),
                       grower="masked", hist_method="scatter")
    cfg_c = cfg_m._replace(grower="compact")
    tm, rlm = grow_tree(cfg_m, bins, g, h, w, fm, fnb, fnan)
    tc, rlc = grow_tree(cfg_c, bins, g, h, w, fm, fnb, fnan)
    np.testing.assert_array_equal(np.asarray(tm.split_feature),
                                  np.asarray(tc.split_feature))
    np.testing.assert_array_equal(np.asarray(rlm), np.asarray(rlc))
    np.testing.assert_allclose(np.asarray(tm.leaf_value),
                               np.asarray(tc.leaf_value),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("quantized", [False, True])
def test_compact_grower_multi_chunk_windows(quantized):
    """Pin a small streaming chunk so leaf windows span SEVERAL chunks:
    exercises the telescoping scratch appends, the rot alignment and the
    merge write-back of the chunked partition (single-chunk windows
    cannot catch regressions there)."""
    args = _mk(6000, 5, 32, seed=6)
    cfg_m = GrowConfig(num_leaves=12, num_bins=32,
                       split=SplitParams(min_data_in_leaf=5.0),
                       grower="masked", hist_method="scatter",
                       quantized=quantized, stochastic=False)
    cfg_c = cfg_m._replace(grower="compact", chunk=512)
    tm, rlm = grow_tree(cfg_m, *args)
    tc, rlc = grow_tree(cfg_c, *args)
    if quantized:
        # the masked grower has no quantized path; compare the chunked
        # compact grower against the single-chunk compact grower instead
        tc1, rlc1 = grow_tree(cfg_c._replace(chunk=16384), *args)
        tm, rlm = tc1, rlc1
    assert int(tm.num_leaves) == int(tc.num_leaves)
    for name in ("split_feature", "threshold_bin", "leaf_count",
                 "left_child", "right_child"):
        np.testing.assert_array_equal(np.asarray(getattr(tm, name)),
                                      np.asarray(getattr(tc, name)),
                                      err_msg=name)
    np.testing.assert_array_equal(np.asarray(rlm), np.asarray(rlc))
    np.testing.assert_allclose(np.asarray(tm.leaf_value),
                               np.asarray(tc.leaf_value),
                               atol=1e-4, rtol=1e-4)


def test_hist_from_rows_int_exact():
    """int8 nibble histogram is exact integer arithmetic."""
    from lightgbm_tpu.ops.histogram import hist_from_rows_int
    rs = np.random.RandomState(5)
    S, F, B = 20000, 5, 130  # crosses ROW_BLOCK=16384, s_hi=9
    rows = rs.randint(0, B, size=(S, F)).astype(np.uint8)
    pay = rs.randint(-4, 5, size=(S, 3)).astype(np.int8)
    out = np.asarray(hist_from_rows_int(jnp.asarray(rows),
                                        jnp.asarray(pay), B))
    ref = np.zeros((F, B, 3), np.int64)
    for f in range(F):
        for c in range(3):
            np.add.at(ref[f, :, c], rows[:, f], pay[:, c])
    np.testing.assert_array_equal(out, ref)
