# tpulint fixture: TPL010 positives — device collectives inside
# traced-conditional branches with no replicated-cond justification.
import jax
import jax.numpy as jnp
from jax import lax


def _window_reduce(x, axis):
    """Local helper that transitively dispatches a device collective —
    the ops/grow.py window_hist -> hist_psum shape."""
    return lax.psum(jnp.sum(x), axis)


def lambda_branch_direct(pred, x, axis):
    """Collective lexically inside a cond branch lambda."""
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: lax.psum(x, axis),
                    lambda: x)


def lambda_branch_through_helper(pred, x, axis):
    """The hazard one call level down: the branch calls a local
    function that reaches lax.psum through the call graph."""
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: _window_reduce(x, axis),
                    lambda: jnp.sum(x))


def _miss_branch(x, axis):
    return _window_reduce(x, axis)


def named_branch_function(pred, x, axis):
    """A function reference (not a lambda) as the diverging branch."""
    # EXPECT: TPL010
    return lax.cond(pred, _miss_branch, jnp.sum, x, axis)


def switch_branch(idx, x, axis):
    """lax.switch: one arm of the branch list gathers."""
    # EXPECT: TPL010
    return lax.switch(idx, [lambda: jnp.sum(x),
                            lambda: lax.pmax(jnp.max(x), axis)])


def keyword_branch_form(pred, x, axis):
    """Branches passed as keywords are the same hazard."""
    # EXPECT: TPL010
    return lax.cond(pred,
                    true_fun=lambda: lax.psum(x, axis),
                    false_fun=lambda: x)


class _Pool:
    def _miss(self, x, axis):
        return _window_reduce(x, axis)

    def attribute_branch(self, pred, x, axis):
        """An attribute reference (bound method) as the branch."""
        # EXPECT: TPL010
        return lax.cond(pred, self._miss, lambda *a: a[0], x, axis)

    def lambda_calls_method(self, pred, x, axis):
        """The branch lambda reaches the collective through a METHOD
        call — the refactor shape that must not slip past."""
        # EXPECT: TPL010
        return lax.cond(pred,
                        lambda: self._miss(x, axis),
                        lambda: x)


def bare_pragma_does_not_suppress(pred, x, axis):
    """A replicated-cond mark WITHOUT a why is a suppressed deadlock,
    not an accepted invariant — still flagged."""
    # EXPECT: TPL010
    return lax.cond(pred,  # tpulint: replicated-cond
                    lambda: lax.psum(x, axis),
                    lambda: x)
