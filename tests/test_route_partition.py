"""Butterfly-route partition (ops/grow.py route_concentrate).

The compact grower's in-chunk stable partition ships as LSB-first
butterfly concentration routing (GrowConfig.partition="route"); the
variadic-sort path remains as "sort". These tests pin:
- the routing primitive against a host-side stable compaction, across
  exhaustive small chunks and randomized large ones (the
  congestion-freedom of order-preserving partial routes is a theorem,
  but the implementation's bit plumbing is what can rot);
- tree-for-tree equality of the two partition modes through the full
  grower, the same equivalence bar tests/test_grower_equivalence.py
  holds the masked/compact pair to.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.grow import GrowConfig, grow_tree, route_concentrate
from lightgbm_tpu.ops.split import SplitParams


def _host_route(mark, col, offset):
    out = np.full(col.shape, -1, col.dtype)
    out[offset:offset + mark.sum()] = col[mark]
    return out


@pytest.mark.parametrize("k", [2, 4, 8])
def test_route_concentrate_exhaustive_small(k):
    f = jax.jit(route_concentrate)
    for bits in range(2 ** k):
        mark = np.array([(bits >> i) & 1 for i in range(k)], bool)
        cnt = int(mark.sum())
        for offset in (0, (k - cnt) // 2, k - cnt):
            col = np.arange(k, dtype=np.int32)
            (out,) = f((jnp.asarray(col),), jnp.asarray(mark),
                       jnp.int32(offset))
            got = np.asarray(out)[offset:offset + cnt]
            want = col[mark]
            assert np.array_equal(got, want), (k, bits, offset)


def test_route_concentrate_randomized_large():
    rs = np.random.RandomState(7)
    for _ in range(40):
        k = 2 ** rs.randint(5, 13)
        mark = rs.rand(k) < rs.rand()
        cnt = int(mark.sum())
        offset = int(rs.randint(0, k - cnt + 1))
        cols = (np.arange(k, dtype=np.int32),
                rs.randint(0, 2 ** 31, size=k).astype(np.uint32),
                rs.randn(k).astype(np.float32))
        outs = route_concentrate(tuple(jnp.asarray(c) for c in cols),
                                 jnp.asarray(mark), jnp.int32(offset))
        sel = slice(offset, offset + cnt)
        for c, o in zip(cols, outs):
            assert np.array_equal(np.asarray(o)[sel], c[mark])


def test_route_pair_kernel_matches_xla_route():
    """The Pallas pair kernel (ops/partition_kernel.py route_pair) in
    interpret mode against the XLA route — the oracle relationship the
    module docstring promises. (On-TPU the kernel is currently slower
    than the in-situ sort and unused; see benchmarks/PROFILE.md.)"""
    from lightgbm_tpu.ops.partition_kernel import (route_pair,
                                                   stack_cols,
                                                   unstack_cols)
    rs = np.random.RandomState(11)
    for k in (256, 1024):
        cols = (jnp.asarray(rs.randint(0, 2 ** 31, size=k)
                            .astype(np.uint32)),
                jnp.asarray(rs.randn(k).astype(np.float32)))
        r = rs.rand(k)
        vl = jnp.asarray(r < 0.35)
        vr = jnp.asarray((r >= 0.35) & (r < 0.9))
        rc = int(np.sum((r >= 0.35) & (r < 0.9)))
        lc = int(np.sum(r < 0.35))
        A, spec = stack_cols(cols)
        L, R = route_pair(A, vl, vr, interpret=True)
        lops = unstack_cols(L, spec)
        rops = unstack_cols(R, spec)
        l_ref = route_concentrate(cols, vl, jnp.int32(0))
        r_ref = route_concentrate(cols, vr, jnp.int32(k - rc))
        for a, b in zip(lops, l_ref):
            assert np.array_equal(np.asarray(a)[:lc],
                                  np.asarray(b)[:lc])
        for a, b in zip(rops, r_ref):
            assert np.array_equal(np.asarray(a)[k - rc:],
                                  np.asarray(b)[k - rc:])


def _grow(partition, bins_T, grad, hess, num_leaves=31, chunk=512,
          quantized=False):
    F = bins_T.shape[0]
    cfg = GrowConfig(num_leaves=num_leaves, num_bins=64,
                     split=SplitParams(), hist_method="scatter",
                     grower="compact", chunk=chunk, partition=partition,
                     quantized=quantized)
    n = bins_T.shape[1]
    return grow_tree(cfg, bins_T, grad, hess,
                     jnp.ones((n,), jnp.float32),
                     jnp.ones((F,), bool),
                     jnp.full((F,), 64, jnp.int32),
                     jnp.full((F,), -1, jnp.int32),
                     quant_key=(jax.random.PRNGKey(3) if quantized
                                else None))


@pytest.mark.parametrize("n,chunk", [(1000, 512), (4096, 512),
                                     (777, 256), (513, 1024)])
def test_grower_route_equals_sort(n, chunk):
    rs = np.random.RandomState(0)
    F = 9
    bins_T = jnp.asarray(rs.randint(0, 64, size=(F, n), dtype=np.uint8))
    grad = jnp.asarray(rs.randn(n).astype(np.float32))
    hess = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))
    t_r, rl_r = _grow("route", bins_T, grad, hess, chunk=chunk)
    t_s, rl_s = _grow("sort", bins_T, grad, hess, chunk=chunk)
    assert np.array_equal(np.asarray(rl_r), np.asarray(rl_s))
    for a, b in zip(t_r, t_s):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_grower_route_equals_sort_quantized():
    rs = np.random.RandomState(1)
    F, n = 6, 2000
    bins_T = jnp.asarray(rs.randint(0, 64, size=(F, n), dtype=np.uint8))
    grad = jnp.asarray(rs.randn(n).astype(np.float32))
    hess = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))
    t_r, rl_r = _grow("route", bins_T, grad, hess, quantized=True)
    t_s, rl_s = _grow("sort", bins_T, grad, hess, quantized=True)
    assert np.array_equal(np.asarray(rl_r), np.asarray(rl_s))
    for a, b in zip(t_r, t_s):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_grower_nibble_packed_low_bin():
    """B <= 16 streams bins at 8 columns per u32 word (the 4-bit
    DenseBin analog); the packed path must match the scatter-method
    masked grower tree-for-tree."""
    import lightgbm_tpu as lgb
    rs = np.random.RandomState(5)
    n = 3000
    X = rs.randn(n, 7)
    y = ((X[:, 0] - 0.5 * X[:, 1]) > 0).astype(float)
    base = {"objective": "binary", "num_leaves": 31, "max_bin": 15,
            "min_data_in_leaf": 5, "verbosity": -1}
    compact = lgb.train({**base, "grower": "compact"},
                        lgb.Dataset(X, label=y), num_boost_round=4)
    masked = lgb.train({**base, "grower": "masked"},
                       lgb.Dataset(X, label=y), num_boost_round=4)
    np.testing.assert_allclose(compact.predict(X[:400]),
                               masked.predict(X[:400]), rtol=1e-5)


def test_grower_wide_gather_equals_sort(monkeypatch):
    """The wide partition (sort (key, iota) + row gathers of the packed
    words; grow.py make_body) must be bit-identical to the
    payload-carrying sort it replaces past _SORT_SINGLE_MAX operands.
    F=64 u8 -> NW=16 word columns engages the gather path at the
    default threshold; forcing the threshold sky-high re-takes the
    sort path on the identical inputs."""
    import lightgbm_tpu.ops.grow as growmod
    rs = np.random.RandomState(7)
    F, n = 64, 5000
    bins_T = jnp.asarray(rs.randint(0, 64, size=(F, n), dtype=np.uint8))
    grad = jnp.asarray(rs.randn(n).astype(np.float32))
    hess = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))
    t_g, rl_g = _grow("sort", bins_T, grad, hess)
    monkeypatch.setattr(growmod, "_SORT_SINGLE_MAX", 10_000)
    t_s, rl_s = _grow("sort", bins_T, grad, hess)
    assert np.array_equal(np.asarray(rl_g), np.asarray(rl_s))
    for a, b in zip(t_g, t_s):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_grower_wide_gather_equals_sort_tracked_bf16(monkeypatch):
    """Same A/B with the ord2-tracking + packed-payload variant (the
    bundled/TPU configuration folds pay and ord into the gathered word
    block — exercise that lane too)."""
    import lightgbm_tpu.ops.grow as growmod
    rs = np.random.RandomState(8)
    F, n = 64, 4096
    bins_T = jnp.asarray(rs.randint(0, 64, size=(F, n), dtype=np.uint8))
    grad = jnp.asarray(rs.randn(n).astype(np.float32))
    hess = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))

    def grow_tracked():
        cfg = GrowConfig(num_leaves=31, num_bins=64,
                         split=SplitParams(), hist_method="scatter",
                         grower="compact", chunk=512, partition="sort",
                         track_rows=True)
        return grow_tree(cfg, bins_T, grad, hess,
                         jnp.ones((n,), jnp.float32),
                         jnp.ones((F,), bool),
                         jnp.full((F,), 64, jnp.int32),
                         jnp.full((F,), -1, jnp.int32))

    t_g, rl_g = grow_tracked()
    monkeypatch.setattr(growmod, "_SORT_SINGLE_MAX", 10_000)
    t_s, rl_s = grow_tracked()
    assert np.array_equal(np.asarray(rl_g), np.asarray(rl_s))
    for a, b in zip(t_g, t_s):
        assert np.array_equal(np.asarray(a), np.asarray(b))
