import sys

if __name__ == "__main__":
    # `lint` runs the jax-free static analyzer (lightgbm_tpu/analysis/);
    # dispatch it BEFORE importing the training CLI, whose module
    # imports pull in jax — tpulint must work where no backend can
    # initialize.
    if len(sys.argv) > 1 and sys.argv[1] == "lint":
        from .analysis.cli import main as lint_main
        raise SystemExit(lint_main(sys.argv[2:]))

    # `launch` is the elastic restart supervisor (resilience/elastic.py):
    # it must not import jax either — the supervisor outlives dying
    # worker worlds and must never pin the accelerator devices the
    # workers need.
    if len(sys.argv) > 1 and sys.argv[1] == "launch":
        from .resilience.elastic import main as launch_main
        raise SystemExit(launch_main(sys.argv[2:]))

    from .cli import main
    raise SystemExit(main())
