"""IR-contract lint (``lint --ir``, analysis/ircheck.py): TPL011-014.

Four layers, mirroring tests/test_static_analysis.py's structure:

1. End-to-end: the shipped tree lowers clean — every entry in the
   ircheck signature table, zero findings, inside the wall-clock
   budget, with the committed tools/ir_budgets.json neither stale nor
   unjustified.
2. Per-rule IR fixtures (tests/analysis_fixtures/ir/): one positive
   and one negative per rule, pinned by ``# EXPECT: TPLNNN`` markers
   (the marker names the line that FOLLOWS it, same convention as the
   AST fixtures) and cross-checked by finding id + line.
3. Mutation regressions on the REAL tree: three hand-applied
   regressions (sharded search's psum_scatter replaced by a full
   psum, the fused scan's donation dropped, an np.float64 constant
   injected into a traced helper) each must fail ``lint --ir`` in a
   subprocess with the exact expected finding id.
4. Consistency: the static declaration surface (register_jit AST
   sites, TPL014's input) must cover what a real training run
   actually compiles — every runtime-tracked entry point appears in
   the static scan and stays within its declared max_signatures.
"""

import ast
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
IR_FIXTURES = os.path.join(HERE, "analysis_fixtures", "ir")
_MARKER = "/analysis_fixtures/ir/"

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(TPL\d{3})\s*$")


def _expected_findings(rel):
    out = []
    with open(os.path.join(IR_FIXTURES, rel), encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                out.append((m.group(1), i + 1))
    return sorted(out)


def _anchor_line(rel, name):
    """Line of the top-level ``NAME = ...`` assignment in a fixture —
    where entry-level findings (budget/donation) anchor."""
    with open(os.path.join(IR_FIXTURES, rel), encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
            return node.lineno
    raise AssertionError(f"{rel}: no top-level {name} assignment")


def _load_fixture(rel):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "ir_fixture_" + rel.replace(".py", ""),
        os.path.join(IR_FIXTURES, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check(findings, rel):
    from lightgbm_tpu.analysis.baseline import assign_ids
    assign_ids(findings)
    got = sorted((f.rule, f.lineno) for f in findings)
    expected = _expected_findings(rel)
    assert got == expected, (
        f"{rel}: findings diverge from # EXPECT markers\n"
        f"  expected: {expected}\n  got:      {got}\n  "
        + "\n  ".join(f"{f.fid} @ {f.lineno}: {f.message[:100]}"
                      for f in findings))
    for f in findings:
        assert f.fid.startswith(f"{f.rule}:{f.relpath}:"), f.fid


# ---------------------------------------------------------------------
# 1. end-to-end on the shipped tree
# ---------------------------------------------------------------------

def test_ir_lint_clean_on_tree(monkeypatch):
    """The committed tree lowers clean at every declared signature,
    the budget file is fully justified and non-stale, and the whole
    pass stays inside the CI wall-clock budget."""
    from lightgbm_tpu.analysis.ircheck import run_ircheck
    # run_ircheck setdefaults this; pin it via monkeypatch so the
    # in-process run can't leak the forced donation into later tests
    monkeypatch.setenv("LIGHTGBM_TPU_FORCE_DONATE", "1")
    res = run_ircheck()
    assert not res.findings, "\n".join(
        f"{f.rule} {f.relpath}:{f.lineno} {f.message}"
        for f in res.findings)
    assert not res.stale_budget, [e.fid for e in res.stale_budget]
    assert not res.unjustified_budget, \
        [e.fid for e in res.unjustified_budget]
    assert len(res.entries_run) == 11, res.entries_run
    assert "parallel/dp_grow@wide-sharded" in res.entries_run
    assert res.elapsed < 60.0, f"IR pass took {res.elapsed:.1f}s"


def test_budget_file_pins_acceptance_entries():
    """tools/ir_budgets.json commits the wide-sharded payload bound
    and the scan-carry donation contract the ISSUE acceptance names."""
    with open(os.path.join(REPO, "tools", "ir_budgets.json"),
              encoding="utf-8") as fh:
        entries = json.load(fh)["entries"]
    wide = entries["parallel/dp_grow@wide-sharded"]
    # post-reduction must stay well under wire: that gap IS the
    # sharded-search cut a full-psum regression would erase
    assert wide["post_reduction_bytes"] * 4 < wide["wire_bytes"]
    assert entries["gbdt/fused_scan@W4"]["donate_argnums"] == [0, 1]
    assert entries["gbdt/fused_iter@default"]["donate_argnums"] == [0]
    for key, val in entries.items():
        just = str(val.get("justification", "")).strip()
        assert just and not just.upper().startswith("TODO"), key


def test_load_budgets_rejects_todo_justification(tmp_path):
    from lightgbm_tpu.analysis.ircheck import load_budgets
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"entries": {
        "a@x": {"wire_bytes": 1, "justification": "TODO: later"},
        "b@y": {"wire_bytes": 1, "justification": "real reason"},
    }}))
    _, unjustified = load_budgets(str(p))
    assert [e.fid for e in unjustified] == ["ir_budgets.json:a@x"]


# ---------------------------------------------------------------------
# 2. per-rule fixtures
# ---------------------------------------------------------------------

@pytest.mark.parametrize("rel", ["tpl011_pos.py", "tpl011_neg.py"])
def test_tpl011_fixture(rel):
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from lightgbm_tpu.analysis.ircheck import f64_findings
    fn, args = _load_fixture(rel).build(jax, jnp)
    with enable_x64():
        closed = jax.make_jaxpr(fn)(*args)
    _check(f64_findings(closed, rel, "build", f"fixture/{rel}",
                        marker=_MARKER), rel)


@pytest.mark.parametrize("rel", ["tpl012_pos.py", "tpl012_neg.py"])
def test_tpl012_fixture(rel):
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.ircheck import IRSpec, budget_findings
    from lightgbm_tpu.parallel.comms import collective_summary
    mod = _load_fixture(rel)
    fn, args = mod.build(jax, jnp)
    spec = IRSpec(entry=f"fixture/{rel}", relpath=rel, func="build",
                  signature="", build=None,
                  lineno=_anchor_line(rel, "BUDGET"))
    closed = jax.make_jaxpr(fn)(*args)
    _check(budget_findings(collective_summary(closed), mod.BUDGET,
                           spec), rel)


@pytest.mark.parametrize("rel", ["tpl013_pos.py", "tpl013_neg.py"])
def test_tpl013_fixture(rel):
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.ircheck import IRSpec, donation_findings
    mod = _load_fixture(rel)
    jit_fn, args = mod.build(jax, jnp)
    spec = IRSpec(entry=f"fixture/{rel}", relpath=rel, func="build",
                  signature="", build=None,
                  lineno=_anchor_line(rel, "DONATE"))
    _check(donation_findings(jit_fn, args, mod.DONATE, spec), rel)


@pytest.mark.parametrize("rel", ["tpl014_pos.py", "tpl014_neg.py"])
def test_tpl014_fixture(rel):
    from lightgbm_tpu.analysis.ircheck import recompile_surface_findings
    findings = [f for f in recompile_surface_findings(IR_FIXTURES)
                if f.relpath == rel]
    _check(findings, rel)


def test_every_ir_rule_has_fixture_coverage():
    from lightgbm_tpu.analysis import IR_RULES
    covered = set()
    for rel in sorted(os.listdir(IR_FIXTURES)):
        if rel.endswith(".py"):
            for rule, _ in _expected_findings(rel):
                covered.add(rule)
    missing = {r.id for r in IR_RULES} - covered
    assert not missing, f"IR rules without a positive fixture: {missing}"


# ---------------------------------------------------------------------
# 3. mutation regressions on the real tree
# ---------------------------------------------------------------------

def _mutated_lint(tmp_path, relpath, old, new, entry):
    """Copy lightgbm_tpu + tools into tmp, apply one source mutation,
    and run ``lint --ir`` there in a subprocess (ircheck lowers the
    IMPORTED package, so the mutated copy must be what resolves)."""
    pkg = tmp_path / "lightgbm_tpu"
    shutil.copytree(os.path.join(REPO, "lightgbm_tpu"), pkg,
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copytree(os.path.join(REPO, "tools"), tmp_path / "tools")
    target = pkg / relpath
    src = target.read_text(encoding="utf-8")
    assert src.count(old) == 1, \
        f"{relpath}: mutation anchor not unique ({src.count(old)} hits)"
    target.write_text(src.replace(old, new), encoding="utf-8")
    env = dict(os.environ, PYTHONPATH=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "lint", "--ir",
         "--ir-entry", entry, "--format", "json"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 1, (
        f"mutated lint --ir rc={proc.returncode} (want 1)\n"
        f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}")
    return [f["id"] for f in json.loads(proc.stdout)["findings"]]


def test_mutation_full_psum_trips_collective_budget(tmp_path):
    """Regressing sharded search to a full psum (+ slice) multiplies
    the post-reduction payload ~D x past the committed budget."""
    fids = _mutated_lint(
        tmp_path, "ops/grow.py",
        "            return lax.psum_scatter(\n"
        "                x, cfg.axis_name, scatter_dimension=ax,\n"
        "                tiled=True), ef\n",
        "            full = lax.psum(x, cfg.axis_name)\n"
        "            return lax.dynamic_slice_in_dim(\n"
        "                full, dev_idx * (x.shape[ax] // D_sh),\n"
        "                x.shape[ax] // D_sh, axis=ax), ef\n",
        "parallel/dp_grow@wide-sharded")
    assert ("TPL012:parallel/data_parallel.py:make_dp_grow_fn:"
            "ir-budget#1") in fids, fids


def test_mutation_dropped_donation_trips_tpl013(tmp_path):
    """Dropping donate_argnums from the fused scan wrapper leaves the
    budget-declared carry donation unhonored in the lowered program."""
    fids = _mutated_lint(
        tmp_path, "models/gbdt.py",
        "jax.jit(scan_fn, donate_argnums=_donate(0, 1)),",
        "jax.jit(scan_fn),",
        "gbdt/fused_scan@W4")
    assert ("TPL013:models/gbdt.py:GBDTBooster._get_scan_fn:"
            "ir-donation#1") in fids, fids


def test_mutation_float64_constant_trips_tpl011(tmp_path):
    """An np.float64 constant in a traced helper becomes a strong f64
    aval under the x64 trace — the dtype-contract leak TPL011 exists
    to catch (the AST rule TPL009 can only see syntactic producers)."""
    fids = _mutated_lint(
        tmp_path, "ops/split.py",
        "    return t * t / (sum_h + p.lambda_l2 + K_EPS)\n",
        "    import numpy as np\n"
        "    return t * t / (sum_h + p.lambda_l2 + K_EPS) "
        "* np.float64(1.0)\n",
        "ops/grow_tree@narrow")
    assert "TPL011:ops/split.py:leaf_gain:ir-f64#1" in fids, fids


# ---------------------------------------------------------------------
# 4. static declarations vs runtime recompile counters
# ---------------------------------------------------------------------

def test_static_declarations_cover_runtime_recompiles():
    """Train for a few rounds and predict, then cross-check the
    runtime jit tracker against the static surface TPL014 scans:
    every entry point the run actually compiled must be a
    register_jit site in the source, carry a max_signatures
    declaration, and stay within it."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.analysis.engine import package_root
    from lightgbm_tpu.analysis.ircheck import register_jit_sites
    from lightgbm_tpu.obs import jit_cache_sizes, jit_declarations

    rs = np.random.RandomState(7)
    X = rs.randn(256, 8)
    y = (X[:, 0] + 0.3 * rs.randn(256) > 0).astype(np.float64)
    bst = lgb.train(dict(objective="binary", num_leaves=7, max_bin=63,
                         verbosity=-1),
                    lgb.Dataset(X, label=y), num_boost_round=3)
    bst.predict(X)

    static_names = {s["name"]
                    for s in register_jit_sites(package_root())
                    if s["name"]}
    declared = jit_declarations()
    sizes = jit_cache_sizes()
    assert sizes, "training tracked no jitted entry points"
    for (name, _), size in sizes.items():
        assert name in static_names, (
            f"runtime entry {name!r} has no register_jit site the "
            f"static scan can find")
        assert name in declared, (
            f"runtime entry {name!r} compiled without a "
            f"max_signatures declaration")
        assert size <= declared[name], (
            f"{name}: {size} live signatures exceeds the declared "
            f"max_signatures={declared[name]}")
