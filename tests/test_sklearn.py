"""sklearn-wrapper behavior (model: reference tests/python_package_test/
test_sklearn.py — estimator compliance, eval sets, fitted attributes)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from tests.conftest import make_synthetic_binary, make_synthetic_regression


def test_classifier_string_labels():
    X, y = make_synthetic_binary(n=600, f=6)
    ylab = np.where(y > 0, "pos", "neg")
    clf = lgb.LGBMClassifier(n_estimators=12, num_leaves=15, random_state=1)
    clf.fit(X[:500], ylab[:500], eval_set=[(X[500:], ylab[500:])],
            eval_metric="binary_logloss")
    pred = clf.predict(X[500:])
    proba = clf.predict_proba(X[500:])
    assert set(pred) <= {"pos", "neg"}
    assert (pred == ylab[500:]).mean() > 0.75
    assert proba.shape == (100, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    assert list(clf.classes_) == ["neg", "pos"]
    assert clf.n_classes_ == 2
    assert clf.feature_importances_.shape == (6,)
    assert "valid_0" in clf.evals_result_


def test_regressor_and_clone():
    from sklearn.base import clone
    X, y = make_synthetic_regression(n=600, f=6)
    reg = lgb.LGBMRegressor(n_estimators=8, num_leaves=15)
    reg.fit(X[:500], y[:500])
    r2 = 1 - np.mean((reg.predict(X[500:]) - y[500:]) ** 2) / np.var(y[500:])
    assert r2 > 0.5
    reg2 = clone(reg)
    assert reg2.get_params()["n_estimators"] == 8
    with pytest.raises(lgb.LightGBMError):
        reg2.predict(X)  # not fitted


def test_multiclass_classifier():
    rs = np.random.RandomState(5)
    X = rs.randn(500, 6)
    y = np.digitize(X @ rs.randn(6), [-1, 1])
    clf = lgb.LGBMClassifier(n_estimators=6, num_leaves=7)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X[:50])
    assert proba.shape == (50, 3)
    acc = (clf.predict(X) == y).mean()
    assert acc > 0.6


def test_ranker_requires_group():
    X, y = make_synthetic_binary(n=200, f=4)
    rk = lgb.LGBMRanker(n_estimators=3, num_leaves=7)
    with pytest.raises(ValueError):
        rk.fit(X, y)
    rk.fit(X, (y * 3).astype(int), group=[50, 50, 50, 50])
    assert rk.predict(X).shape == (200,)


def test_custom_objective_callable():
    X, y = make_synthetic_regression(n=400, f=5)

    def mse_obj(y_true, y_pred):
        return (y_pred - y_true), np.ones_like(y_true)

    reg = lgb.LGBMRegressor(n_estimators=8, num_leaves=15,
                            objective=mse_obj)
    reg.fit(X, y)
    pred = reg.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.8
