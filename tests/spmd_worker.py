"""Worker for the real 2-process SPMD test (test_multiprocess.py).

Each process owns 2 virtual CPU devices and one contiguous row shard;
together they form a 4-device global mesh — the same topology as two
single-chip hosts on DCN. Run as:

    python spmd_worker.py <rank> <coordinator_port> <outdir>
"""

import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
outdir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from lightgbm_tpu.parallel.distributed import init_distributed  # noqa: E402

init_distributed(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=2, process_id=rank)
assert jax.process_count() == 2
assert len(jax.devices()) == 4

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.parallel import spmd  # noqa: E402

rs = np.random.RandomState(0)
n, f = 2000, 6
X = rs.randn(n, f)
y = ((X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2]
      + 0.1 * rs.randn(n)) > 0).astype(float)
half = n // 2
lo, hi = rank * half, (rank + 1) * half

ds = spmd.distributed_dataset(X[lo:hi], label=y[lo:hi],
                              params={"verbosity": -1})
bst = lgb.train({"objective": "binary", "num_leaves": 15,
                 "min_data_in_leaf": 5, "tree_learner": "data",
                 "verbosity": -1}, ds, num_boost_round=5)

# every process computes the identical replicated model; process 0
# writes it (the Dask layer's "keep worker 0's model",
# python-package/lightgbm/dask.py:_train_part)
if rank == 0:
    bst.save_model(os.path.join(outdir, "model_mp.txt"))
print(f"rank {rank} DONE", flush=True)
