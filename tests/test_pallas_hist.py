"""Interpret-mode parity suite for the Pallas histogram kernel.

``hist_method="pallas"`` (ops/pallas_hist.py) runs the SAME kernel on
CPU under ``pallas_call(..., interpret=True)`` that a TPU runs
natively; these tests prove it numerically equal to the mxu and
scatter paths — bit-exact for int8-quantized payloads, within the mxu
path's documented float tolerance otherwise — across bin widths
(u8/u16), payload dtypes, and padded/non-multiple shapes, plus the
selection / fallback logic (``auto``, the kill switch, the OOM
degradation ladder rung) and whole-tree growth parity.
"""
import json

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import resolve_hist_method
from lightgbm_tpu.ops.histogram import (build_histogram, hist_from_rows,
                                        hist_from_rows_int)
from lightgbm_tpu.ops.pallas_hist import (INT_BLOCK, hist_from_rows_pallas,
                                          pallas_available)

# the float bar: the mxu path's own multi-pass tolerance class
# (tests/test_grower_equivalence.py::test_hist_mxu_matches_scatter) —
# both pallas and mxu accumulate in f32 on CPU, differing from the
# scatter path only in summation order
FLOAT_TOL = dict(atol=2e-3, rtol=1e-4)


def _ref_hist(rows, pay, B):
    F = rows.shape[1]
    out = np.zeros((F, B, pay.shape[1]), np.float64)
    for f in range(F):
        np.add.at(out[f], rows[:, f], pay.astype(np.float64))
    return out


def test_pallas_importable_here():
    """Tier-1 runs the kernel under the interpreter: the environment
    must expose pallas (if this ever fails, the parity suite below is
    silently vacuous — fail loudly instead)."""
    assert pallas_available()


@pytest.mark.parametrize("S,F,B", [
    (5000, 11, 67),      # nothing aligned: F % FPACK != 0, B % 128 != 0
    (512, 8, 128),       # everything exactly tile-aligned
    (130, 1, 2),         # single feature, tiny row count, 2 bins
    (4097, 9, 255),      # one row past a tile, odd feature count
])
def test_float_parity_u8(S, F, B):
    rs = np.random.RandomState(3)
    rows = rs.randint(0, B, (S, F)).astype(np.uint8)
    pay = np.stack([rs.randn(S), rs.rand(S)], axis=1).astype(np.float32)
    got = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="pallas"))
    ref = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="scatter"))
    assert got.shape == (F, B, 2)
    np.testing.assert_allclose(got, ref, **FLOAT_TOL)
    mxu = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="mxu"))
    np.testing.assert_allclose(got, mxu, **FLOAT_TOL)
    np.testing.assert_allclose(got, _ref_hist(rows, pay, B), **FLOAT_TOL)


def test_float_parity_u16_wide_bins():
    """u16 bin columns with B > 256 (the bundled/EFB bin-position
    regime)."""
    rs = np.random.RandomState(4)
    S, F, B = 3000, 5, 300
    rows = rs.randint(0, B, (S, F)).astype(np.uint16)
    pay = np.stack([rs.randn(S), rs.rand(S)], axis=1).astype(np.float32)
    got = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="pallas"))
    ref = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="scatter"))
    np.testing.assert_allclose(got, ref, **FLOAT_TOL)


def test_wide_bins_shrinks_feature_pack():
    """B in the thousands (bundled EFB bin positions): the tile plan
    halves the feature pack so the VMEM one-hot block stays bounded;
    results must be unchanged."""
    from lightgbm_tpu.ops.pallas_hist import _tile_plan
    fp, rt = _tile_plan(2048)
    assert fp < 8 and rt >= 128 and 128 * fp * 2048 * 4 <= 4 * 2 ** 20
    # the budget holds at every realistic padded width, including the
    # fp==1 regime where only the row tile is left to shrink
    for bp in (128, 256, 1024, 4096, 16384, 131072):
        fp_b, rt_b = _tile_plan(bp)
        assert rt_b * fp_b * bp * 4 <= 4 * 2 ** 20, (bp, fp_b, rt_b)
        assert rt_b >= 8 and rt_b & (rt_b - 1) == 0
    rs = np.random.RandomState(13)
    S, F, B = 900, 3, 1500
    rows = rs.randint(0, B, (S, F)).astype(np.uint16)
    pay = np.stack([rs.randn(S), rs.rand(S)], axis=1).astype(np.float32)
    got = np.asarray(hist_from_rows(jnp.asarray(rows), jnp.asarray(pay),
                                    B, method="pallas"))
    np.testing.assert_allclose(got, _ref_hist(rows, pay, B), **FLOAT_TOL)


def test_int8_payload_bit_exact():
    """Quantized path: int8 (g, h) payloads must accumulate to the
    EXACT int32 histogram (subtraction-safety depends on it)."""
    rs = np.random.RandomState(5)
    S, F, B = 7001, 6, 255
    rows = rs.randint(0, B, (S, F)).astype(np.uint8)
    pay = rs.randint(-127, 128, (S, 2)).astype(np.int8)
    got = np.asarray(hist_from_rows_int(jnp.asarray(rows),
                                        jnp.asarray(pay), B,
                                        method="pallas"))
    assert got.dtype == np.int32
    mxu = np.asarray(hist_from_rows_int(jnp.asarray(rows),
                                        jnp.asarray(pay), B,
                                        method="mxu"))
    assert np.array_equal(got, mxu)
    ref = _ref_hist(rows, pay, B).astype(np.int64)
    assert np.array_equal(got.astype(np.int64), ref)


def test_int8_blocked_accumulation_exact():
    """Row counts past INT_BLOCK exercise the per-super-block int32
    conversion (f32 accumulation alone would lose integer exactness
    past 2^24)."""
    rs = np.random.RandomState(6)
    S, F, B = INT_BLOCK + 9000, 2, 16
    rows = rs.randint(0, B, (S, F)).astype(np.uint8)
    pay = np.full((S, 2), 127, np.int8)  # worst case magnitudes
    got = np.asarray(hist_from_rows_pallas(jnp.asarray(rows),
                                           jnp.asarray(pay), B,
                                           int_exact=True))
    ref = _ref_hist(rows, pay, B).astype(np.int64)
    assert np.array_equal(got.astype(np.int64), ref)


def test_sibling_subtraction_consistency():
    """The histogram-subtraction trick the growers rely on: for any
    row split, hist(parent) - hist(child) must equal hist(sibling) —
    bit-exact in the quantized path, within float tolerance otherwise
    (the compact/level growers recover every big sibling this way)."""
    rs = np.random.RandomState(7)
    S, F, B = 6000, 9, 63
    rows = rs.randint(0, B, (S, F)).astype(np.uint8)
    left = rs.rand(S) < 0.37
    # float payload
    pay = np.stack([rs.randn(S), rs.rand(S)], axis=1).astype(np.float32)
    h_all = hist_from_rows(jnp.asarray(rows), jnp.asarray(pay), B,
                           method="pallas")
    h_left = hist_from_rows(jnp.asarray(rows),
                            jnp.asarray(pay * left[:, None]), B,
                            method="pallas")
    sib = np.asarray(h_all - h_left)
    ref = np.asarray(hist_from_rows(
        jnp.asarray(rows), jnp.asarray(pay * ~left[:, None]), B,
        method="pallas"))
    np.testing.assert_allclose(sib, ref, atol=5e-3, rtol=1e-4)
    # int8 payload: exactly
    payi = rs.randint(-127, 128, (S, 2)).astype(np.int8)
    hi_all = hist_from_rows_int(jnp.asarray(rows), jnp.asarray(payi), B,
                                method="pallas")
    hi_left = hist_from_rows_int(
        jnp.asarray(rows), jnp.asarray(payi * left[:, None]), B,
        method="pallas")
    hi_right = hist_from_rows_int(
        jnp.asarray(rows), jnp.asarray(payi * ~left[:, None]), B,
        method="pallas")
    assert np.array_equal(np.asarray(hi_all - hi_left),
                          np.asarray(hi_right))


def test_build_histogram_mask_and_weights():
    """The grower-facing entry: leaf mask + bagging weights fold into
    the payload identically across methods."""
    rs = np.random.RandomState(8)
    F, n, B = 7, 4000, 31
    bins_T = jnp.asarray(rs.randint(0, B, (F, n)).astype(np.uint8))
    g = jnp.asarray(rs.randn(n).astype(np.float32))
    h = jnp.asarray(rs.rand(n).astype(np.float32))
    w = jnp.asarray((rs.rand(n) > 0.3).astype(np.float32) * 1.7)
    mask = jnp.asarray(rs.rand(n) > 0.5)
    a = build_histogram(bins_T, g, h, w, mask, B, "scatter")
    b = build_histogram(bins_T, g, h, w, mask, B, "pallas")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), **FLOAT_TOL)


# ---------------------------------------------------------------------
# whole-tree parity (the kernel inside the jitted growers)
# ---------------------------------------------------------------------

def _grow_args(n=5000, F=7, B=31, seed=0):
    from lightgbm_tpu.ops.grow import GrowConfig  # noqa: F401
    rs = np.random.RandomState(seed)
    bins = jnp.asarray(rs.randint(0, B, (F, n)).astype(np.uint8))
    g = jnp.asarray(rs.randn(n).astype(np.float32))
    h = jnp.asarray((np.abs(rs.randn(n)) + 0.1).astype(np.float32))
    w = jnp.ones((n,), jnp.float32)
    return (bins, g, h, w, jnp.ones((F,), bool),
            jnp.full((F,), B, jnp.int32), jnp.full((F,), -1, jnp.int32))


@pytest.mark.parametrize("quant", [False, True])
def test_compact_grower_tree_parity(quant):
    """grow_tree(hist_method=pallas) builds the identical tree to the
    scatter and mxu paths — structure exactly, float-search thresholds
    included (ties would diverge loudly here)."""
    from lightgbm_tpu.ops.grow import GrowConfig, grow_tree
    import jax

    args = _grow_args()
    trees = {}
    for m in ("scatter", "mxu", "pallas"):
        cfg = GrowConfig(num_leaves=15, num_bins=31, hist_method=m,
                         chunk=1024, quantized=quant)
        extra = {}
        if quant:
            extra = dict(quant_key=jax.random.PRNGKey(0))
        trees[m] = grow_tree(cfg, *args, **extra)
    tS, rlS = trees["scatter"]
    for m in ("mxu", "pallas"):
        t, rl = trees[m]
        assert int(t.num_leaves) == int(tS.num_leaves)
        assert np.array_equal(np.asarray(t.split_feature),
                              np.asarray(tS.split_feature)), m
        assert np.array_equal(np.asarray(t.threshold_bin),
                              np.asarray(tS.threshold_bin)), m
        assert np.array_equal(np.asarray(rl), np.asarray(rlS)), m
        np.testing.assert_allclose(np.asarray(t.leaf_value),
                                   np.asarray(tS.leaf_value),
                                   rtol=1e-4, atol=1e-5)


def test_engine_end_to_end_pallas_matches_scatter():
    """Full lgb.train through the fused step with hist_method=pallas:
    same trees as the scatter run (structure exact)."""
    rs = np.random.RandomState(9)
    X = rs.randn(2500, 8).astype(np.float32)
    y = ((X @ rs.randn(8)) > 0).astype(np.float64)
    models = {}
    for m in ("scatter", "pallas"):
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        models[m] = lgb.train(
            {"objective": "binary", "num_leaves": 12, "max_bin": 63,
             "hist_method": m, "verbosity": -1}, ds, num_boost_round=4)
    a, b = models["scatter"], models["pallas"]
    assert b._engine.grow_cfg.hist_method == "pallas"
    for ta, tb in zip(a._models, b._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn],
                              tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------
# selection + fallback
# ---------------------------------------------------------------------

def test_resolve_hist_method_matrix(monkeypatch):
    assert resolve_hist_method("auto", "cpu", True) == "scatter"
    assert resolve_hist_method("auto", "tpu", True) == "mxu"
    assert resolve_hist_method("mxu", "cpu", True) == "mxu"
    assert resolve_hist_method("scatter", "tpu", True) == "scatter"
    assert resolve_hist_method("pallas", "tpu", True) == "pallas"
    # the auto -> pallas flip is gated on the measured bench win;
    # LIGHTGBM_TPU_AUTO_PALLAS=1 is the flip switch
    monkeypatch.setenv("LIGHTGBM_TPU_AUTO_PALLAS", "1")
    assert resolve_hist_method("auto", "tpu", True) == "pallas"
    assert resolve_hist_method("auto", "cpu", True) == "scatter"
    # unavailable pallas: auto and the explicit request both fall back
    assert resolve_hist_method("auto", "tpu", False) == "mxu"
    assert resolve_hist_method("pallas", "tpu", False) == "mxu"
    assert resolve_hist_method("pallas", "cpu", False) == "scatter"


def test_kill_switch_disables_pallas(monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_DISABLE_PALLAS", "1")
    assert not pallas_available()
    assert resolve_hist_method("pallas", "cpu") == "scatter"
    monkeypatch.delenv("LIGHTGBM_TPU_DISABLE_PALLAS")
    assert pallas_available()


def test_config_accepts_and_validates():
    from lightgbm_tpu.config import Config
    assert Config(hist_method="pallas").hist_method == "pallas"
    with pytest.raises(ValueError, match="hist_method"):
        Config(hist_method="vmem")


def test_precision_knob_warns_on_pallas(monkeypatch):
    """hist_precision multi-pass emulation is mxu-only: selecting
    pallas with a non-default precision must say so, not silently
    ignore the knob."""
    import lightgbm_tpu.utils.log as log_mod
    seen = []
    monkeypatch.setattr(log_mod, "log_warning",
                        lambda msg: seen.append(msg))
    rs = np.random.RandomState(14)
    X = rs.randn(600, 5).astype(np.float32)
    y = ((X @ rs.randn(5)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "max_bin": 31, "hist_method": "pallas",
                     "hist_precision": "highest", "verbosity": -1},
                    ds, num_boost_round=2)
    assert any("hist_precision" in m for m in seen), seen
    assert bst._engine.grow_cfg.hist_method == "pallas"


def test_oom_ladder_steps_pallas_to_mxu(tmp_path, monkeypatch):
    """The degradation ladder's new first rung: an injected
    RESOURCE_EXHAUSTED on a pallas run sheds to mxu (then the existing
    mxu -> scatter -> pool rungs apply), recorded as a fault event."""
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "oom@1")
    rs = np.random.RandomState(10)
    X = rs.randn(1200, 6).astype(np.float32)
    y = ((X @ rs.randn(6)) > 0).astype(np.float64)
    tpath = str(tmp_path / "t.jsonl")
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "max_bin": 31, "hist_method": "pallas",
                     "verbosity": -1}, ds, num_boost_round=4,
                    callbacks=[lgb.telemetry(tpath)])
    assert bst.current_iteration() == 4
    assert bst._engine.grow_cfg.hist_method == "mxu"
    events = [json.loads(l) for l in open(tpath) if l.strip()]
    oom = [e for e in events if e["event"] == "fault"
           and e["kind"] == "oom"]
    assert oom and "pallas -> mxu" in oom[0]["action"]
