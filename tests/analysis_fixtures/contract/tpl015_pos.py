"""TPL015 positives: emission and consumer drift from the registry."""


def emit(log, extra):
    # EXPECT: TPL015
    log.append({"event": "pingg", "seq": 1})
    # EXPECT: TPL015
    log.append({"event": "ping", "seq": 2, "color": "red"})
    # EXPECT: TPL015
    log.append({"event": "ping"})


def consume(events):
    total = 0
    for ev in events:
        # EXPECT: TPL015
        if ev.get("event") == "pingg":
            continue
        if ev.get("event") != "ping":
            continue
        # EXPECT: TPL015
        total += ev["volume"]
    return total
