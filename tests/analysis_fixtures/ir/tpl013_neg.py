"""TPL013 negative: same-shape donate — the lowered program carries
one ``tf.aliasing_output`` marker for the donated input, honoring the
declaration, so no finding."""


def build(jax, jnp):
    fn = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
    return fn, (jnp.ones((8,), jnp.float32),)


DONATE = (0,)
