# tpulint fixture: TPL008 negative — the same recorder-with-drain-
# thread as obs/tpl008_pos.py, with every thread-shared field guarded
# by a lock COMMON to both sides (proved on the lock-acquisition CFG,
# including an acquire()/release() pair). No EXPECT lines.
import threading

_events = []
_events_lock = threading.Lock()


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []
        self._drainer = threading.Thread(target=self._drain,
                                         daemon=True)
        self._drainer.start()

    def _drain(self):
        while True:
            with self._lock:
                self.pending.clear()

    def snapshot(self):
        with self._lock:
            return list(self.pending)


def _worker():
    _events_lock.acquire()
    _events.append({"event": "fault"})
    _events_lock.release()


def start_worker():
    threading.Thread(target=_worker).start()
    with _events_lock:
        return list(_events)


def _queue_worker(q):
    # synchronization primitives are exempt: a Queue orders handoffs
    q.put({"event": "fault"})


def start_queue_worker():
    import queue
    q = queue.Queue()
    threading.Thread(target=_queue_worker, args=(q,)).start()
    return q.get()
