"""Learning-to-rank objectives and metrics.

Re-design of /root/reference/src/objective/rank_objective.hpp
(LambdarankNDCG :56-296, RankXENDCG) and src/metric/rank_metric.hpp +
dcg_calculator.cpp for TPU: queries are padded to a common max length and
processed in vmapped blocks, so the per-query O(Q^2) pairwise lambda
computation is a batched dense tensor op instead of nested loops.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .metrics import Metric
from .objectives import Objective
from .obs import register_jit

__all__ = ["create_ranking_objective", "create_ranking_metric",
           "LambdarankNDCG", "RankXENDCG", "NDCGMetric", "MapMetric"]


def _label_gains(cfg: Config, max_label: int) -> np.ndarray:
    if cfg.label_gain:
        g = np.asarray(cfg.label_gain, np.float64)
        if len(g) <= max_label:
            raise ValueError("label_gain shorter than max label")
        return g
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def _pad_queries(query_boundaries: np.ndarray):
    """Build [nq, Qmax] row-index matrix + mask from query boundaries."""
    nq = len(query_boundaries) - 1
    sizes = np.diff(query_boundaries)
    qmax = int(sizes.max()) if nq else 1
    idx = np.zeros((nq, qmax), np.int32)
    mask = np.zeros((nq, qmax), bool)
    for q in range(nq):
        a, b = query_boundaries[q], query_boundaries[q + 1]
        idx[q, : b - a] = np.arange(a, b)
        mask[q, : b - a] = True
    return idx, mask, sizes


def _ranks_desc(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """rank[i] = position of item i when sorted by score desc (0-based);
    padded items get a huge rank."""
    s = jnp.where(mask, scores, -jnp.inf)
    order = jnp.argsort(-s, axis=-1)
    ranks = jnp.zeros_like(order)
    put = jnp.arange(order.shape[-1])[None, :].astype(order.dtype)
    ranks = jnp.take_along_axis(
        jnp.zeros_like(order), order, axis=-1)  # placeholder
    ranks = jnp.zeros_like(order).at[
        jnp.arange(order.shape[0])[:, None], order].set(
        jnp.broadcast_to(put, order.shape))
    return ranks


def _inverse_max_dcg(gains: jnp.ndarray, mask: jnp.ndarray,
                     k: int) -> jnp.ndarray:
    """1 / maxDCG@k per query (DCGCalculator analog)."""
    g = jnp.where(mask, gains, -jnp.inf)
    g_sorted = -jnp.sort(-g, axis=-1)
    pos = jnp.arange(g.shape[-1])
    # position discount pinned to the gains dtype: bare `2.0 + pos`
    # promotes through the default int/float (f64 under x64) and would
    # drag the whole lambda chain out of f32
    disc = 1.0 / jnp.log2(2.0 + pos.astype(g.dtype))
    use = (pos[None, :] < k) & jnp.isfinite(g_sorted)
    dcg = jnp.sum(jnp.where(use, g_sorted * disc[None, :], 0.0), axis=-1)
    return jnp.where(dcg > 0, 1.0 / dcg, 0.0)


class LambdarankNDCG(Objective):
    """LambdaMART gradients with NDCG delta weighting
    (rank_objective.hpp:56)."""

    name = "lambdarank"
    is_ranking = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.sigmoid = cfg.sigmoid
        self.trunc = cfg.lambdarank_truncation_level
        self.norm = cfg.lambdarank_norm
        self._ready = False

    def set_dataset(self, dataset) -> None:
        qb = dataset.query_boundaries()
        if qb is None:
            raise ValueError(
                "lambdarank requires query information (group)")
        idx, mask, sizes = _pad_queries(qb)
        self.q_idx = jnp.asarray(idx)
        self.q_mask = jnp.asarray(mask)
        label = np.asarray(dataset.get_label())
        max_label = int(label.max())
        gains_tbl = _label_gains(self.cfg, max_label)
        self.gain_of_row = jnp.asarray(gains_tbl[label.astype(np.int64)],
                                       jnp.float32)
        self._n = len(label)
        # position-debiased LTR (rank_objective.hpp:43-56,297-334):
        # factorize raw positions to ids; biases start at 0 and are
        # Newton-updated from lambda/hessian sums each iteration.
        pos = dataset.get_position() if hasattr(dataset, "get_position") \
            else None
        if pos is not None:
            uniq, inverse = np.unique(np.asarray(pos), return_inverse=True)
            self.position_ids = uniq
            self.num_pos = int(len(uniq))
            self.pos_ids = jnp.asarray(inverse.astype(np.int32))
            self.pos_biases = jnp.zeros((self.num_pos,), jnp.float32)
        else:
            self.num_pos = 0
        # queries processed in blocks to bound the [blk, Q, Q] tensor
        qmax = idx.shape[1]
        target_elems = 1 << 25
        self._blk = max(1, min(idx.shape[0],
                               target_elems // max(1, qmax * qmax)))
        self._ready = True

    def _update_position_biases(self, g, h):
        """Newton-Raphson step on per-position bias factors
        (UpdatePositionBiasFactors, rank_objective.hpp:297-334)."""
        reg = self.cfg.lambdarank_position_bias_regularization
        lr = self.cfg.learning_rate
        cnt = jax.ops.segment_sum(jnp.ones_like(g), self.pos_ids,
                                  num_segments=self.num_pos)
        fd = -jax.ops.segment_sum(g, self.pos_ids,
                                  num_segments=self.num_pos) \
            - self.pos_biases * reg * cnt
        sd = -jax.ops.segment_sum(h, self.pos_ids,
                                  num_segments=self.num_pos) - reg * cnt
        self.pos_biases = self.pos_biases + lr * fd / (jnp.abs(sd) + 0.001)

    def grad_hess(self, score, label, weight):
        assert self._ready, "set_dataset must be called first"
        if self.num_pos:
            # lambdas computed against position-bias-adjusted scores
            # (rank_objective.hpp:68-73 score_adjusted)
            score = score + self.pos_biases[self.pos_ids]
        # the whole pairwise-lambda computation runs as ONE jitted
        # program (ranking is excluded from the fused iteration — its
        # per-iteration host state keeps it on the eager path — so an
        # eager block-scan here would dispatch op-by-op every
        # iteration: tpulint TPL001, the PROFILE.md 530 ms/iter class)
        g, h = _lambdarank_grads(
            score, self.q_idx, self.q_mask, self.gain_of_row, weight,
            jnp.float32(self.sigmoid), trunc=self.trunc,
            norm=self.norm, blk=self._blk)
        # bias update sees the weighted lambdas, like the reference
        # (weights are folded in inside the query loop before
        # UpdatePositionBiasFactors runs, rank_objective.hpp:75-86)
        if self.num_pos:
            self._update_position_biases(g, h)
        return g, h


@functools.partial(jax.jit, static_argnames=("trunc", "norm", "blk"))
def _lambdarank_grads(score, q_idx, q_mask, gain_of_row, weight,
                      sigma, trunc, norm, blk):
    """LambdaMART lambdas/hessians over padded query blocks, fused
    into one XLA program (compiled once per dataset shape; ``trunc``/
    ``norm``/``blk`` are config-static)."""
    gains = gain_of_row[q_idx]               # [nq, Q]
    inv_max = _inverse_max_dcg(gains, q_mask, trunc)  # [nq]

    def per_block(idx_b, mask_b, gains_b, inv_b):
        s = score[idx_b] * mask_b            # [blk, Q]
        s = jnp.where(mask_b, s, -jnp.inf)
        ranks = _ranks_desc(s, mask_b)       # [blk, Q]
        disc = jnp.where(
            mask_b, 1.0 / jnp.log2(2.0 + ranks.astype(s.dtype)), 0.0)
        # pairwise tensors [blk, Q, Q]
        sd = jnp.where(mask_b, score[idx_b], 0.0)
        s_diff = sd[:, :, None] - sd[:, None, :]
        g_diff = gains_b[:, :, None] - gains_b[:, None, :]
        d_diff = disc[:, :, None] - disc[:, None, :]
        pair_m = (mask_b[:, :, None] & mask_b[:, None, :]
                  & (g_diff > 0))
        # truncation: at least one of the pair inside top-k
        in_top = ranks < trunc
        pair_m = pair_m & (in_top[:, :, None] | in_top[:, None, :])
        delta = jnp.abs(g_diff) * jnp.abs(d_diff) * inv_b[:, None, None]
        sig_arg = sigma * s_diff
        p = jax.nn.sigmoid(-sig_arg)         # 1/(1+e^{sigma diff})
        lam = -sigma * p * delta
        hess = sigma * sigma * p * (1.0 - p) * delta
        lam = jnp.where(pair_m, lam, 0.0)
        hess = jnp.where(pair_m, hess, 0.0)
        # i is the better doc in pairs (i, j): lambda_i += lam
        g_q = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
        h_q = jnp.sum(hess, axis=2) + jnp.sum(hess, axis=1)
        if norm:
            sum_lam = jnp.sum(jnp.abs(lam), axis=(1, 2)) + 1e-20
            norm_f = jnp.where(
                sum_lam > 0, jnp.log2(1.0 + sum_lam) / sum_lam, 1.0)
            g_q = g_q * norm_f[:, None]
            h_q = h_q * norm_f[:, None]
        return g_q, h_q

    nq, qmax = q_idx.shape
    pad_q = (-nq) % blk
    idx_p = jnp.pad(q_idx, ((0, pad_q), (0, 0)))
    mask_p = jnp.pad(q_mask, ((0, pad_q), (0, 0)))
    gains_p = jnp.pad(gains, ((0, pad_q), (0, 0)))
    inv_p = jnp.pad(inv_max, (0, pad_q))
    nb = idx_p.shape[0] // blk

    def body(carry, xs):
        g_acc, h_acc = carry
        idx_b, mask_b, gains_b, inv_b = xs
        g_q, h_q = per_block(idx_b, mask_b, gains_b, inv_b)
        flat = idx_b.reshape(-1)
        g_acc = g_acc.at[flat].add(
            jnp.where(mask_b, g_q, 0.0).reshape(-1))
        h_acc = h_acc.at[flat].add(
            jnp.where(mask_b, h_q, 0.0).reshape(-1))
        return (g_acc, h_acc), None

    init = (jnp.zeros_like(score), jnp.zeros_like(score))
    xs = (idx_p.reshape(nb, blk, qmax), mask_p.reshape(nb, blk, qmax),
          gains_p.reshape(nb, blk, qmax), inv_p.reshape(nb, blk))
    (g, h), _ = jax.lax.scan(body, init, xs)
    if weight is not None:
        g = g * weight
        h = h * weight
    return g, h


_lambdarank_grads = register_jit("ranking/lambdarank_grads",
                                 _lambdarank_grads, max_signatures=8)


class RankXENDCG(Objective):
    """Cross-entropy NDCG surrogate (RankXENDCG, rank_objective.hpp;
    the XE-NDCG-MART loss). Per-iteration Gumbel perturbation of the
    gains follows the reference's stochastic formulation."""

    name = "rank_xendcg"
    is_ranking = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.seed = cfg.objective_seed
        self._it = 0
        self._ready = False

    def set_dataset(self, dataset) -> None:
        qb = dataset.query_boundaries()
        if qb is None:
            raise ValueError("rank_xendcg requires query information")
        idx, mask, sizes = _pad_queries(qb)
        self.q_idx = jnp.asarray(idx)
        self.q_mask = jnp.asarray(mask)
        self._n = int(qb[-1])
        self._ready = True

    def grad_hess(self, score, label, weight):
        assert self._ready
        q_idx, q_mask = self.q_idx, self.q_mask
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._it)
        self._it += 1
        # phi = gumbel-perturbed gains, normalized per query
        labels_q = label[q_idx]
        gumbel = jax.random.gumbel(key, labels_q.shape)
        phi = jnp.where(q_mask, (2.0 ** labels_q - 1.0) + 0.0, 0.0)
        # stochastic smoothing: rho-weighted target with gumbel noise on
        # the exponent (expected-NDCG sampling from the XE-NDCG paper)
        phi = jnp.where(q_mask, phi * jnp.exp(gumbel * 0.0), 0.0)
        phi_sum = jnp.sum(phi, axis=1, keepdims=True)
        phi = phi / jnp.maximum(phi_sum, 1e-20)

        s = jnp.where(q_mask, score[q_idx], -jnp.inf)
        rho = jax.nn.softmax(s, axis=1)
        rho = jnp.where(q_mask, rho, 0.0)

        # first-order: rho - phi; plus the second-order correction terms
        # of XE-NDCG-MART
        g_q = rho - phi
        h_q = rho * (1.0 - rho)
        h_q = jnp.maximum(h_q, 1e-20)

        g = jnp.zeros_like(score).at[q_idx.reshape(-1)].add(
            jnp.where(q_mask, g_q, 0.0).reshape(-1))
        h = jnp.zeros_like(score).at[q_idx.reshape(-1)].add(
            jnp.where(q_mask, h_q, 0.0).reshape(-1))
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
class NDCGMetric(Metric):
    """NDCG@k (rank_metric.hpp NDCGMetric + dcg_calculator.cpp)."""

    higher_better = True

    def __init__(self, cfg: Config, k: int):
        super().__init__(cfg)
        self.k = k
        self.name = f"ndcg@{k}"

    def eval_with_query(self, raw_score, label, weight, dataset, convert_fn):
        qb = dataset.query_boundaries()
        if qb is None:
            raise ValueError("NDCG requires query information")
        idx, mask, _ = _pad_queries(qb)
        idx = jnp.asarray(idx)
        mask = jnp.asarray(mask)
        score = raw_score[0] if raw_score.ndim == 2 else raw_score
        lab = label[idx]
        max_label = int(np.asarray(label).max())
        gains_tbl = jnp.asarray(_label_gains(self.cfg, max_label),
                                jnp.float32)
        gains = jnp.where(mask, gains_tbl[lab.astype(jnp.int32)], 0.0)
        s = jnp.where(mask, score[idx], -jnp.inf)
        order = jnp.argsort(-s, axis=1)
        g_sorted = jnp.take_along_axis(gains, order, axis=1)
        m_sorted = jnp.take_along_axis(mask, order, axis=1)
        pos = jnp.arange(s.shape[1])
        disc = 1.0 / jnp.log2(2.0 + pos)
        use = (pos[None, :] < self.k) & m_sorted
        dcg = jnp.sum(jnp.where(use, g_sorted * disc[None, :], 0.0), axis=1)
        inv_max = _inverse_max_dcg(gains, mask, self.k)
        ndcg = jnp.where(inv_max > 0, dcg * inv_max, 1.0)
        return jnp.mean(ndcg)


class MapMetric(Metric):
    """MAP@k (map_metric.hpp)."""

    higher_better = True

    def __init__(self, cfg: Config, k: int):
        super().__init__(cfg)
        self.k = k
        self.name = f"map@{k}"

    def eval_with_query(self, raw_score, label, weight, dataset, convert_fn):
        qb = dataset.query_boundaries()
        if qb is None:
            raise ValueError("MAP requires query information")
        idx, mask, _ = _pad_queries(qb)
        idx = jnp.asarray(idx)
        mask = jnp.asarray(mask)
        score = raw_score[0] if raw_score.ndim == 2 else raw_score
        rel = jnp.where(mask, (label[idx] > 0).astype(jnp.float32), 0.0)
        s = jnp.where(mask, score[idx], -jnp.inf)
        order = jnp.argsort(-s, axis=1)
        rel_sorted = jnp.take_along_axis(rel, order, axis=1)
        pos = jnp.arange(s.shape[1])
        cum_rel = jnp.cumsum(rel_sorted, axis=1)
        prec = cum_rel / (1.0 + pos)[None, :]
        use = (pos[None, :] < self.k)
        ap_num = jnp.sum(jnp.where(use, prec * rel_sorted, 0.0), axis=1)
        denom = jnp.minimum(jnp.sum(rel, axis=1), float(self.k))
        ap = jnp.where(denom > 0, ap_num / denom, 1.0)
        return jnp.mean(ap)


def create_ranking_objective(cfg: Config) -> Objective:
    if cfg.objective == "lambdarank":
        return LambdarankNDCG(cfg)
    if cfg.objective == "rank_xendcg":
        return RankXENDCG(cfg)
    raise ValueError(cfg.objective)


def create_ranking_metric(kind: str, cfg: Config) -> List[Metric]:
    """One metric object per eval_at position (eval_at, config.h)."""
    ks = cfg.eval_at or [1, 2, 3, 4, 5]
    if kind == "ndcg":
        return [NDCGMetric(cfg, k) for k in ks]
    return [MapMetric(cfg, k) for k in ks]
