"""The tpulint rule set (TPL001-TPL009). Pure stdlib.

Each rule is a class with a stable ``id``, a one-line ``title``, and a
``run(ctx)`` generator yielding :class:`Finding`. Rules see the whole
:class:`~lightgbm_tpu.analysis.callgraph.CallGraph` (jit-reachability,
call records, hot markers) plus the raw ASTs, and are scoped to the
hot-path files by the engine. The statement-level rules TPL001-TPL006
live here; the CFG/dataflow rules TPL007-TPL009 live in
:mod:`~lightgbm_tpu.analysis.rules_flow` and are re-registered into
``ALL_RULES`` below. docs/STATIC_ANALYSIS.md documents each rule's
hazard, an example, the fix, and how to baseline.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .astscan import ModuleScan, dotted_of
from .callgraph import CallGraph, CallRecord, Key

__all__ = ["Finding", "Rule", "ALL_RULES", "IR_RULES", "rule_by_id",
           "LintContext"]

_LAX_LOOPS = {"fori_loop", "scan", "while_loop"}

#: host-synchronizing calls (dotted externals)
_SYNC_DOTTED = {"numpy.asarray", "numpy.array", "jax.device_get"}
#: host-synchronizing method calls
_SYNC_METHODS = {"item", "block_until_ready"}


@dataclass
class Finding:
    rule: str
    relpath: str
    lineno: int
    col: int
    func: str              # enclosing qualname or "<module>"
    symbol: str            # what was matched (feeds the stable id)
    message: str
    fid: str = ""          # assigned by the engine (stable id)

    def sort_key(self):
        return (self.relpath, self.lineno, self.col, self.rule)


@dataclass
class LintContext:
    graph: CallGraph
    scans: Dict[str, ModuleScan]
    scope: Set[str]                      # relpaths the rules run over
    root: str = ""                       # package dir (doc checks only)

    def scoped_scans(self) -> Iterator[ModuleScan]:
        for rel in sorted(self.scope):
            if rel in self.scans:
                yield self.scans[rel]

    def scope_of_node(self, scan: ModuleScan, lineno: int) -> str:
        """Innermost enclosing function qualname for a line."""
        best = "<module>"
        best_span = None
        for qual, info in scan.funcs.items():
            if info.lineno <= lineno <= info.end_lineno:
                span = info.end_lineno - info.lineno
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def is_traced(self, key: Optional[Key]) -> bool:
        return key is not None and key in self.graph.jit_reachable

    def is_hot(self, key: Optional[Key]) -> bool:
        if key is None:
            return False
        info = self.graph.funcs.get(key)
        if info is None:
            return False
        while info is not None:
            if info.is_hot:
                return True
            info = self.graph.funcs.get(
                (info.relpath, info.parent_qual)) \
                if info.parent_qual else None
        return False


class Rule:
    id = "TPL000"
    title = "abstract rule"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        raise NotImplementedError

    def _finding(self, ctx: LintContext, relpath: str, node,
                 symbol: str, message: str,
                 func: Optional[str] = None) -> Finding:
        scan = ctx.scans[relpath]
        qual = func if func is not None \
            else ctx.scope_of_node(scan, node.lineno)
        return Finding(rule=self.id, relpath=relpath,
                       lineno=node.lineno, col=node.col_offset,
                       func=qual, symbol=symbol, message=message)


# ---------------------------------------------------------------------
class EagerLaxLoop(Rule):
    """TPL001: a ``lax.fori_loop`` / ``lax.scan`` / ``lax.while_loop``
    whose enclosing function is not jit-reachable dispatches op-by-op
    through the device tunnel — the PROFILE.md 530 ms/iter class."""

    id = "TPL001"
    title = "eager lax loop outside a jit-reachable function"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scope, facts in ctx.graph.facts.items():
            for rec in facts.records:
                if rec.relpath not in ctx.scope:
                    continue
                name = None
                if rec.kind == "ext" and rec.dotted:
                    base = rec.dotted.rsplit(".", 1)[-1]
                    root = rec.dotted.split(".", 1)[0]
                    if base in _LAX_LOOPS and root in ("jax", "lax"):
                        name = base
                elif rec.kind == "method" and rec.attr in _LAX_LOOPS:
                    name = rec.attr
                if name is None:
                    continue
                if ctx.is_traced(scope):
                    continue
                func = scope[1] if scope else "<module>"
                yield self._finding(
                    ctx, rec.relpath, rec.node, f"lax.{name}",
                    f"lax.{name} in {func}() which is not jit-reachable "
                    "(no proof every entry goes through a jax.jit/"
                    "pjit/shard_map wrapper): this dispatches eagerly, "
                    "op-by-op — the PROFILE.md 530 ms/iter class. Put "
                    "it behind a jitted entry point (and register_jit "
                    "it) or delete dead code.", func=func)


# ---------------------------------------------------------------------
class HostSync(Rule):
    """TPL002: host-device synchronization inside jit-reachable or
    per-iteration hot code (``# tpulint: hot``-marked drivers)."""

    id = "TPL002"
    title = "host sync in jit-reachable or hot per-iteration code"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scope, facts in ctx.graph.facts.items():
            if scope is None:
                continue
            traced = ctx.is_traced(scope)
            hot = ctx.is_hot(scope)
            if not (traced or hot):
                continue
            where = "jit-reachable (traced)" if traced else \
                "per-iteration hot"
            for rec in facts.records:
                if rec.relpath not in ctx.scope:
                    continue
                sym = self._sync_symbol(rec, facts, traced)
                if sym is None:
                    continue
                yield self._finding(
                    ctx, rec.relpath, rec.node, sym,
                    f"{sym} in {scope[1]}() which is {where} code: "
                    "this forces a host-device round trip "
                    "(or a trace-time concretization error) and "
                    "serializes the device pipeline. Keep data on "
                    "device, or move the fetch onto the async "
                    "one-iteration-late queue "
                    "(copy_to_host_async + deferred read).",
                    func=scope[1])

    def _sync_symbol(self, rec: CallRecord, facts,
                     traced: bool) -> Optional[str]:
        if rec.kind == "ext" and rec.dotted in _SYNC_DOTTED:
            short = {"numpy.asarray": "np.asarray",
                     "numpy.array": "np.array",
                     "jax.device_get": "jax.device_get"}[rec.dotted]
            if traced and not self._touches_param(rec, facts):
                return None     # trace-time constant table building
            return short
        if rec.kind == "method" and rec.attr in _SYNC_METHODS:
            return f".{rec.attr}()"
        if traced and rec.kind == "builtin" \
                and rec.dotted in ("float", "int"):
            if rec.node.args and not isinstance(rec.node.args[0],
                                                ast.Constant) \
                    and self._touches_param(rec, facts):
                return f"{rec.dotted}()"
        return None

    @staticmethod
    def _touches_param(rec: CallRecord, facts) -> bool:
        """Does the call's argument expression reference a function
        parameter (i.e. likely a tracer, not a trace-time constant)?"""
        for arg in list(rec.node.args) \
                + [kw.value for kw in rec.node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) \
                        and sub.id in facts.param_names:
                    return True
        return False


# ---------------------------------------------------------------------
class RecompileHazard(Rule):
    """TPL003: recompile storms — a ``jax.jit`` constructed inside a
    loop (a fresh wrapper = a fresh compile cache), or data-derived
    Python scalars/tuples flowing into ``static_argnums`` /
    ``static_argnames`` (every new value is a new trace signature)."""

    id = "TPL003"
    title = "recompile hazard (jit-in-loop / data-derived static arg)"

    _DERIVERS = {"int", "float", "bool", "tuple", "list"}
    _DERIVER_METHODS = {"item", "tolist"}

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scope, facts in ctx.graph.facts.items():
            for rec in facts.records:
                if rec.relpath not in ctx.scope:
                    continue
                yield from self._jit_in_loop(ctx, rec, scope)
                yield from self._static_args(ctx, rec)

    def _jit_in_loop(self, ctx, rec: CallRecord, scope):
        from .astscan import jit_wrap_kind
        if rec.kind != "ext" or not rec.in_loop:
            return
        if jit_wrap_kind(rec.dotted) is None:
            return
        yield self._finding(
            ctx, rec.relpath, rec.node, "jit-in-loop",
            f"{rec.dotted} constructed inside a loop: every "
            "iteration builds a NEW wrapper with an empty compile "
            "cache, so every call recompiles (the telemetry "
            "`recompiles` counter spikes — docs/OBSERVABILITY.md). "
            "Hoist the jit to module/init scope or memoize it.")

    def _static_args(self, ctx, rec: CallRecord):
        if rec.kind != "wrapper" or rec.wrap is None:
            return
        wrap = rec.wrap
        static_pos = set(wrap.static_argnums or ())
        names = ()
        if wrap.static_argnames and rec.target is not None:
            info = ctx.graph.funcs.get(rec.target)
            if info is not None:
                names = wrap.static_argnames
                for nm in names:
                    if nm in info.params:
                        static_pos.add(info.params.index(nm))
        for i, arg in enumerate(rec.node.args):
            if i in static_pos and self._data_derived(arg):
                yield self._static_finding(ctx, rec, arg, f"arg{i}")
        for kw in rec.node.keywords:
            if kw.arg in (wrap.static_argnames or ()) \
                    and self._data_derived(kw.value):
                yield self._static_finding(ctx, rec, kw.value, kw.arg)

    def _static_finding(self, ctx, rec, node, which):
        return self._finding(
            ctx, rec.relpath, node, f"static-arg:{which}",
            f"static argument {which} is derived from data "
            "(int()/float()/tuple()/.item()/.tolist() of a runtime "
            "value): every distinct value is a distinct trace "
            "signature, so this recompiles per value — the recompile "
            "storm class (docs/OBSERVABILITY.md). Pass it as a traced "
            "array argument, or derive statics from shapes/config "
            "only.")

    def _data_derived(self, node) -> bool:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Name) \
                    and sub.func.id in self._DERIVERS:
                if sub.args and not all(
                        isinstance(a, ast.Constant) for a in sub.args):
                    return True
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in self._DERIVER_METHODS:
                return True
        return False


# ---------------------------------------------------------------------
class DonationViolation(Rule):
    """TPL004: a buffer passed at a ``donate_argnums`` position is
    dead after the call — XLA reuses its memory. Reading it again
    raises "Array has been deleted" (or silently reads garbage on
    backends that skip the check)."""

    id = "TPL004"
    title = "use of a buffer after donate_argnums donation"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scan in ctx.scoped_scans():
            for qual, info in scan.funcs.items():
                yield from self._check_function(ctx, scan, qual, info)

    def _check_function(self, ctx, scan, qual, info):
        facts = ctx.graph.facts.get(info.key)
        if facts is None:
            return
        donations: List[Tuple[str, int, int]] = []  # (name, call line)
        for rec in facts.records:
            if rec.kind != "wrapper" or rec.wrap is None \
                    or not rec.wrap.donate_argnums:
                continue
            for pos in rec.wrap.donate_argnums:
                if pos < len(rec.node.args):
                    nm = self._name_of(rec.node.args[pos])
                    if nm:
                        donations.append((nm, rec.node.lineno,
                                          rec.node.end_lineno or
                                          rec.node.lineno))
        if not donations:
            return
        for nm, lineno, end in donations:
            # a Store on the call's own line is the idiomatic rebind
            # (`score = fused(score, ...)`) — it ends the liveness
            # window immediately. Take the EARLIEST such store by line
            # (ast.walk is breadth-first, so the first hit may be a
            # later but shallower statement).
            end_of_life = min(
                (sub.lineno for sub in ast.walk(info.node)
                 if self._name_of(sub) == nm
                 and isinstance(getattr(sub, "ctx", None), ast.Store)
                 and sub.lineno >= lineno),
                default=None)
            for sub in ast.walk(info.node):
                if self._name_of(sub) == nm \
                        and isinstance(getattr(sub, "ctx", None),
                                       ast.Load) \
                        and sub.lineno > end \
                        and (end_of_life is None
                             or sub.lineno < end_of_life):
                    yield self._finding(
                        ctx, scan.relpath, sub, f"donated:{nm}",
                        f"`{nm}` is read after being donated "
                        f"(donate_argnums call at line {lineno}): the "
                        "buffer was handed to XLA for reuse — this "
                        "read raises \"Array has been deleted\" on "
                        "TPU/GPU. Rebind the result before any "
                        "further use.", func=qual)
                    break

    @staticmethod
    def _name_of(node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return f"self.{node.attr}"
        if isinstance(node, ast.Attribute):
            return None
        return None


# ---------------------------------------------------------------------
class UnorderedIteration(Rule):
    """TPL005: iteration over a ``set`` (or hash-ordered view) where the
    order feeds trace order or collective order. Set order varies with
    PYTHONHASHSEED and across processes — under SPMD each rank would
    trace a different program / join collectives in a different order
    (silent divergence or deadlock)."""

    id = "TPL005"
    title = "order-unstable set/dict iteration feeding trace order"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scan in ctx.scoped_scans():
            in_parallel = scan.relpath.startswith("parallel/")
            for qual, info in scan.funcs.items():
                key = info.key
                relevant = (ctx.is_traced(key) or ctx.is_hot(key)
                            or in_parallel
                            or ctx.graph.dispatches_jax(key))
                if not relevant:
                    continue
                yield from self._check_function(ctx, scan, qual, info)

    def _set_assigns(self, fn_node) -> Dict[str, List[Tuple[int, bool]]]:
        """Per-variable assignment history: (lineno, assigned-a-set).
        Lookups are by line so ``s = {...}; use(s); s = sorted(s)``
        stays precise in straight-line code."""
        out: Dict[str, List[Tuple[int, bool]]] = {}
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                out.setdefault(sub.targets[0].id, []).append(
                    (sub.lineno, self._is_set_expr(sub.value)))
        for hist in out.values():
            hist.sort()
        return out

    @staticmethod
    def _is_set_expr(node) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("set", "frozenset"):
            return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitAnd, ast.BitOr, ast.Sub)):
            return UnorderedIteration._is_set_expr(node.left) \
                or UnorderedIteration._is_set_expr(node.right)
        return False

    def _check_function(self, ctx, scan, qual, info):
        assigns = self._set_assigns(info.node)

        def is_set(node):
            if self._is_set_expr(node):
                return True
            if not isinstance(node, ast.Name):
                return False
            last = None
            for lineno, was_set in assigns.get(node.id, ()):
                if lineno >= node.lineno:
                    break
                last = was_set
            return bool(last)

        for sub in ast.walk(info.node):
            it = None
            how = None
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                it, how, node = sub.iter, "for-loop", sub.iter
            elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp, ast.DictComp)):
                it, how, node = sub.generators[0].iter, \
                    "comprehension", sub.generators[0].iter
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id == "sorted" and sub.args \
                    and is_set(sub.args[0]) \
                    and any(kw.arg == "key" for kw in sub.keywords):
                nm = self._describe(sub.args[0])
                yield self._finding(
                    ctx, scan.relpath, sub, f"set-sorted-key:{nm}",
                    f"sorted({nm}, key=...) over a set: the sort is "
                    "stable, so elements with EQUAL keys keep the "
                    "set's hash order — which varies per process "
                    "(PYTHONHASHSEED) and can diverge across SPMD "
                    "ranks. Build a list (deterministic order) before "
                    "sorting, or sort without ties.", func=qual)
                continue
            if it is None or not is_set(it):
                continue
            yield self._finding(
                ctx, scan.relpath, node,
                f"set-iteration:{self._describe(it)}",
                f"{how} over a set ({self._describe(it)}): set order "
                "varies with PYTHONHASHSEED and across processes. If "
                "it feeds trace order or collective order, SPMD ranks "
                "diverge silently (parallel/spmd.py turns that into a "
                "deadlock-or-error). Iterate sorted(...) or a list "
                "instead.", func=qual)

    @staticmethod
    def _describe(node) -> str:
        d = dotted_of(node)
        if d:
            return d
        return node.__class__.__name__.lower()


# ---------------------------------------------------------------------
class LockAcrossDispatch(Rule):
    """TPL006: a ``threading`` lock held across a jax dispatch in the
    observability or resilience layer. Dispatch can block on the device
    (or on jax's own internal locks); holding a telemetry lock across
    it turns a metrics read on another thread into a pipeline stall —
    or a deadlock if jax re-enters the instrumented path. In
    ``resilience/`` the same shape is worse: the collective watchdog's
    bookkeeping lock held across a *collective* would hang the exact
    abort path that exists to break hangs (watchdog.py's contract is
    copy-under-lock, sync-outside). ``serve/`` inherits the same
    contract: the micro-batcher's lock held across the compiled
    predict dispatch would stall every submit()/stats() caller behind
    one slow device batch."""

    id = "TPL006"
    title = "lock held across jax dispatch in obs/, resilience/, " \
            "serve/ or pipeline.py"

    _SCOPE_PREFIXES = ("obs/", "resilience/", "serve/", "pipeline")
    _LOCK_CALLS = {"Lock", "RLock", "Condition", "Semaphore"}

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        for scan in ctx.scoped_scans():
            if not scan.relpath.startswith(self._SCOPE_PREFIXES):
                continue
            for qual, info in scan.funcs.items():
                yield from self._check_function(ctx, scan, qual, info)

    def _looks_like_lock(self, node) -> bool:
        d = dotted_of(node)
        if d is None:
            if isinstance(node, ast.Call):
                f = dotted_of(node.func) or ""
                return f.rsplit(".", 1)[-1] in self._LOCK_CALLS
            return False
        last = d.rsplit(".", 1)[-1].lower()
        return "lock" in last or "mutex" in last

    def _check_function(self, ctx, scan, qual, info):
        facts = ctx.graph.facts.get(info.key)
        if facts is None:
            return
        for sub in ast.walk(info.node):
            if not isinstance(sub, (ast.With, ast.AsyncWith)):
                continue
            if not any(self._looks_like_lock(item.context_expr)
                       for item in sub.items):
                continue
            lo = sub.lineno
            hi = getattr(sub, "end_lineno", lo)
            for rec in facts.records:
                if not (lo <= rec.node.lineno <= hi):
                    continue
                if ctx.graph.record_dispatches(rec):
                    what = rec.dotted or (
                        f".{rec.attr}()" if rec.attr else "call")
                    yield self._finding(
                        ctx, scan.relpath, rec.node,
                        f"lock-dispatch:{what}",
                        f"jax dispatch ({what}) while holding a lock "
                        f"(with-block at line {lo}): dispatch can "
                        "block on the device, so every other thread "
                        "touching this lock (telemetry snapshots, "
                        "callbacks) stalls with it — and a re-entrant "
                        "path deadlocks. Copy state under the lock, "
                        "dispatch outside it.", func=qual)
                    break


#: imported at the bottom on purpose: rules_flow/rules_contract
#: subclass Rule/use Finding, so they need this module's upper half to
#: exist first. Import THIS module (or the package) for the full rule
#: set.
from .rules_flow import FLOW_RULES  # noqa: E402
from .rules_contract import CONTRACT_RULES  # noqa: E402

ALL_RULES: List[Rule] = [EagerLaxLoop(), HostSync(), RecompileHazard(),
                         DonationViolation(), UnorderedIteration(),
                         LockAcrossDispatch(), *FLOW_RULES,
                         *CONTRACT_RULES]


# ---------------------------------------------------------------------
# IR-contract rules (TPL011-TPL014): descriptors only. The checks run
# in analysis/ircheck.py under ``lint --ir`` — the ONE path that
# imports jax — by lowering every registered entry point at its
# declared signatures and diffing the IR against committed budgets.
# They are deliberately NOT in ALL_RULES: the default AST pass stays
# jax-free and byte-identical, and the AST fixture-coverage test keeps
# its exact TPL001-TPL010 surface.
# ---------------------------------------------------------------------

class IRRule(Rule):
    """Base for lowered-IR rules. ``run`` never yields — findings come
    from :mod:`~lightgbm_tpu.analysis.ircheck`."""

    ir_only = True

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        return iter(())


class DtypeContractIR(IRRule):
    id = "TPL011"
    title = ("f64 op or weak-type widening in lowered IR "
             "(traced under enable_x64; weak scalar plumbing exempt)")


class CollectiveBudgetIR(IRRule):
    id = "TPL012"
    title = ("collective payload exceeds the committed "
             "tools/ir_budgets.json budget (or has none)")


class DonationHonoredIR(IRRule):
    id = "TPL013"
    title = ("declared donate_argnums shows no input->output aliasing "
             "in the lowered program")


class RecompileSurfaceIR(IRRule):
    id = "TPL014"
    title = ("jit entry point without a declared max_signatures "
             "recompile surface (or declaration below the pow2 serve "
             "bucket ladder)")


IR_RULES: List[Rule] = [DtypeContractIR(), CollectiveBudgetIR(),
                        DonationHonoredIR(), RecompileSurfaceIR()]


def rule_by_id(rid: str) -> Optional[Rule]:
    for r in ALL_RULES + IR_RULES:
        if r.id == rid:
            return r
    return None
