"""Fault-tolerant training: survive the failures long accelerator runs
actually hit.

- :mod:`~lightgbm_tpu.resilience.checkpoint` — atomic periodic
  snapshots (model text + score matrix + RNG/bagging state) with
  retention, and ``train(..., resume_from=dir)`` /
  ``LIGHTGBM_TPU_CHECKPOINT`` auto-resume that reproduces the
  uninterrupted model bit-for-bit on CPU.
- non-finite guard — gradients, hessians and fitted leaf values are
  finiteness-checked *inside* the jitted boosting step (one fused
  reduction); the ``nonfinite_policy`` config field picks raise /
  skip_tree / clamp (models/gbdt.py).
- OOM degradation — ``RESOURCE_EXHAUSTED`` during a grow dispatch
  downgrades the histogram path (MXU matmul -> scatter, then histogram
  pool halving) and retries instead of killing the run.
- SPMD sanity guard — :func:`~lightgbm_tpu.parallel.spmd.
  verify_step_consistency` turns silent multi-process divergence into a
  clear ``LightGBMError``.
- :mod:`~lightgbm_tpu.resilience.watchdog` — collective watchdog:
  every host-level sync point of a multi-process run carries a
  deadline, so a rank that dies or stalls mid-collective surfaces as a
  ``LightGBMError`` naming the collective instead of an infinite hang.
- :mod:`~lightgbm_tpu.resilience.elastic` — the supervised restart
  driver (``python -m lightgbm_tpu launch N -- <cmd>``): spawns one
  training process per rank, detects rank death / watchdog aborts,
  and relaunches the world resuming from the newest checkpoint.
- ``init_distributed`` retries its coordinator handshake with
  jittered exponential backoff (parallel/distributed.py) —
  ``init_retries`` / ``init_backoff_seconds`` registry counters.
- :mod:`~lightgbm_tpu.resilience.publisher` — atomic, manifest-first
  model publication into the serve daemon's watch directory with
  jittered retry/backoff: the train -> serve handoff of the
  continuous lifecycle (``python -m lightgbm_tpu pipeline``,
  docs/PIPELINE.md).
- :mod:`~lightgbm_tpu.resilience.faults` — the deterministic
  ``LIGHTGBM_TPU_FAULT_INJECT`` harness the tests drive all of the
  above with (including the distributed kinds ``rank_kill`` /
  ``stall_rank`` / ``init_refuse`` and the lifecycle kinds
  ``publish_torn`` / ``serve_kill`` / ``refit_nan``).

Every fault surfaces as a ``{"event": "fault", ...}`` line in the
telemetry JSONL stream (docs/OBSERVABILITY.md) and a
``fault_events{kind=...}`` registry counter. See docs/RESILIENCE.md.
"""

from . import watchdog
from .checkpoint import (Checkpoint, CheckpointError, checkpoint,
                         list_snapshots, load_latest_snapshot,
                         load_snapshot, restore_booster, snapshot_path,
                         write_snapshot)
from .faults import (FaultPlan, InjectedInitRefused,
                     InjectedResourceExhausted, is_resource_exhausted,
                     record_fault_event)
from .publisher import (PublishError, latest_manifest, load_manifest,
                        manifest_path, publish_model, validate_artifact)

__all__ = [
    "checkpoint", "Checkpoint", "CheckpointError", "snapshot_path",
    "write_snapshot", "load_snapshot", "load_latest_snapshot",
    "list_snapshots", "restore_booster",
    "FaultPlan", "InjectedResourceExhausted", "InjectedInitRefused",
    "is_resource_exhausted", "record_fault_event", "watchdog",
    "PublishError", "publish_model", "manifest_path", "load_manifest",
    "validate_artifact", "latest_manifest",
]
