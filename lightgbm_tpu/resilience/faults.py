"""Deterministic fault injection + device-error classification.

The test harness behind every resilience path: the
``LIGHTGBM_TPU_FAULT_INJECT`` environment variable carries a
comma-separated list of ``kind@iteration`` tokens, e.g.::

    LIGHTGBM_TPU_FAULT_INJECT=nan_grad@7,oom@3,kill@12

Kinds:

- ``nan_grad@N`` / ``nan_hess@N`` — poison the iteration-``N`` gradient
  / hessian vectors with NaN before the tree is grown, exercising the
  non-finite guard (``nonfinite_policy``). Inside the fused jitted step
  the poisoning is traced as a ``where(it == N, ...)`` so the program
  stays a single dispatch.
- ``oom@N`` — raise a synthetic ``RESOURCE_EXHAUSTED`` runtime error at
  the iteration-``N`` grow dispatch. The token is *consumed* on firing,
  so the degradation retry path succeeds (one ``oom@N`` = one transient
  OOM). Repeat the token to simulate back-to-back exhaustion.
- ``kill@N`` — ``SIGKILL`` the current process at the *start* of
  iteration ``N``, exercising checkpoint/auto-resume end to end.

Distributed kinds (docs/RESILIENCE.md "Distributed failures"; the same
``LIGHTGBM_TPU_FAULT_INJECT`` value is typically exported world-wide by
the launch supervisor, so these are additionally gated on
``LIGHTGBM_TPU_FAULT_RANK`` — a comma list of process indices, default
``0`` — and only fire on the matching rank):

- ``rank_kill@N`` — ``SIGKILL`` the selected rank at the start of
  iteration ``N``; the *surviving* ranks then hang in their next host
  collective, which the watchdog (resilience/watchdog.py) converts
  into a ``LightGBMError`` within its deadline. ``N = -1`` fires
  during streaming ingestion instead: right before the pass-1
  bin-mapper sync (``data.ingest.INGEST_FAULT_ITERATION``), so the
  survivors abort naming ``spmd/sync_bin_mappers``.
- ``stall_rank@N`` — the selected rank sleeps forever at the start of
  iteration ``N`` (the straggler / swap-storm failure mode: the
  process is alive, so no transport error ever surfaces — only the
  watchdog deadline catches it).
- ``init_refuse@K`` — ``init_distributed`` raises a synthetic
  connection-refused error on its first ``K`` attempts (coordinator
  not up yet), exercising the retry/backoff loop; fires on every rank.

Lifecycle kinds (docs/PIPELINE.md; the continuous
train -> publish -> serve loop):

- ``publish_torn@G`` — the generation-``G`` model publication
  (resilience/publisher.py) first leaves a TORN artifact behind (a
  truncated model file written non-atomically, the crash-mid-write
  shape the atomic helper exists to prevent) and fails, exercising
  both the publisher's retry/backoff loop and the serve watcher's
  manifest validation + skip-and-retry path.
- ``store_outage@G`` — the generation-``G`` publication's artifact
  store (resilience/store.py) is down for one attempt: the publisher's
  first put raises a transport error, exercising the jittered
  retry/backoff loop over the store interface; the fleet keeps serving
  the current model until the retried publication lands.
- ``publish_poison@G`` — the generation-``G`` publication is
  byte-valid (manifest sha256 matches the model blob) but its canary
  expectations are garbage — the shape of a trainer that published a
  model that scores nonsense. sha256 validation accepts it; only the
  serve-side canary gate (docs/SERVING.md) refuses it, and the fleet
  supervisor rolls the publication back to last-known-good.
- ``serve_kill@N`` — ``SIGKILL`` the serving daemon at its ``N``-th
  accepted predict request, *before* the request enters the batcher
  (an accepted request must never be silently dropped — a killed
  connection is a client-visible error). Gated on
  ``LIGHTGBM_TPU_FAULT_RANK`` against the replica's
  ``LIGHTGBM_TPU_RANK`` (serve replicas are independent single-process
  jax runtimes, so ``jax.process_index()`` cannot tell them apart).
- ``refit_nan@T`` — poison the gradient vector of tree ``T`` during a
  ``Booster.refit`` (warm-start leaf re-derivation), exercising the
  refit-side non-finite guard (``nonfinite_policy``).

A missing / empty variable parses to an inert plan: every query is a
cheap tuple-membership test, nothing touches jax, and production runs
pay nothing.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, List, Tuple

__all__ = ["FaultPlan", "InjectedResourceExhausted", "InjectedInitRefused",
           "is_resource_exhausted", "append_fault_event",
           "record_fault_event", "drain_events", "FAULT_EVENTS"]

#: derived from the single-source fault registry (obs/schemas.py
#: FAULT_KINDS, the TPL018 contract) — one declaration per kind
from ..obs.schemas import injectable_fault_kinds as _injectable_kinds

_KNOWN_KINDS = _injectable_kinds()

#: process-level fault event log for faults that have no engine to hang
#: off (init retries, watchdog timeouts, distributed injections). The
#: telemetry recorder drains it into the JSONL stream alongside the
#: engine ``fault_log``s; capped like them so an undrained process
#: cannot grow it forever.
FAULT_EVENTS: List[dict] = []

#: one process-wide lock for every fault-event log (the global one AND
#: the per-engine ``fault_log``s): appends can come from one thread
#: (a watchdog abort path, a second trainer) while the telemetry
#: recorder drains on another — an unlocked snapshot-then-clear would
#: silently drop every event that landed in between. Critical sections
#: are a list append / a list swap, so one shared lock is cheap.
_EVENTS_LOCK = threading.Lock()


def append_fault_event(log: List[dict], kind: str, iteration: int,
                       action: str, detail: str) -> None:
    """THE fault-event writer: append one ``{"event": "fault"}``
    JSONL-shaped event to ``log`` (capped at 512 so an undrained log
    cannot grow forever), count it in the ``fault_events{kind}``
    registry counter, and warn. Both the engine's per-booster
    ``fault_log`` (``GBDTBooster._record_fault``) and the process-level
    :data:`FAULT_EVENTS` go through here, so the recorder drains one
    schema — and one lock orders appends against
    :func:`drain_events`."""
    with _EVENTS_LOCK:
        if len(log) >= 512:
            del log[0]
        log.append({
            "event": "fault", "kind": kind, "iteration": int(iteration),
            "action": action, "detail": detail, "time": time.time()})
    try:
        from ..obs.registry import registry
        registry.counter("fault_events", kind=kind).inc()
    except Exception:
        pass
    from ..utils.log import log_warning
    log_warning(f"fault[{kind}] at iteration {iteration}: {detail}"
                + (f" -> {action}" if action else ""))


def record_fault_event(kind: str, iteration: int = -1, action: str = "",
                       detail: str = "") -> None:
    """Process-level fault event (no engine in scope): goes to
    :data:`FAULT_EVENTS`."""
    append_fault_event(FAULT_EVENTS, kind, iteration, action, detail)


def drain_events(log: List[dict]) -> List[dict]:
    """Atomically snapshot-and-clear a fault-event log (the global
    :data:`FAULT_EVENTS` or an engine ``fault_log``). The swap happens
    under the same lock :func:`append_fault_event` takes, so an event
    appended concurrently lands either in this drain or in the next —
    never in neither (the lost-event race the telemetry recorder had
    with its bare ``list(log), []`` swap)."""
    with _EVENTS_LOCK:
        events, log[:] = list(log), []
    return events


class InjectedResourceExhausted(RuntimeError):
    """Synthetic stand-in for jaxlib's ``XlaRuntimeError`` OOM: carries
    the same ``RESOURCE_EXHAUSTED`` marker the classifier keys on."""


class InjectedInitRefused(RuntimeError):
    """Synthetic coordinator-not-up failure: carries the ``connection
    refused`` marker ``init_distributed``'s retry classifier keys on."""


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA allocation failures (``XlaRuntimeError`` with a
    RESOURCE_EXHAUSTED status, allocator "out of memory" messages) and
    their injected stand-ins. Message-based on purpose: the concrete
    exception class moved across jaxlib versions."""
    msg = str(exc)
    return ("RESOURCE_EXHAUSTED" in msg
            or "Out of memory" in msg
            or "out of memory" in msg)


class FaultPlan:
    """Parsed ``kind@iteration`` schedule with consume-on-fire
    semantics for ``oom`` (so a retry after degradation succeeds)."""

    def __init__(self, spec: str = ""):
        self._events: Dict[str, List[int]] = {}
        for token in (spec or "").split(","):
            token = token.strip()
            if not token:
                continue
            if "@" not in token:
                raise ValueError(
                    f"bad fault-injection token {token!r} "
                    "(expected kind@iteration)")
            kind, it = token.split("@", 1)
            kind = kind.strip()
            if kind not in _KNOWN_KINDS:
                raise ValueError(
                    f"unknown fault-injection kind {kind!r} "
                    f"(known: {', '.join(_KNOWN_KINDS)})")
            self._events.setdefault(kind, []).append(int(it))
        for lst in self._events.values():
            lst.sort()
        # init_refuse@K: refuse the first K connection attempts
        self._init_refusals_left = sum(self._events.get("init_refuse", ()))

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(os.environ.get("LIGHTGBM_TPU_FAULT_INJECT", ""))

    @property
    def active(self) -> bool:
        return bool(self._events)

    def iters(self, kind: str) -> Tuple[int, ...]:
        """All scheduled iterations for ``kind`` (non-consuming; the
        fused step bakes these into the traced program)."""
        return tuple(self._events.get(kind, ()))

    def fires(self, kind: str, iteration: int) -> bool:
        """Non-consuming membership test (nan_grad / nan_hess)."""
        return iteration in self._events.get(kind, ())

    def take(self, kind: str, iteration: int) -> bool:
        """Consuming test: True once per scheduled token."""
        lst = self._events.get(kind)
        if lst and iteration in lst:
            lst.remove(iteration)
            return True
        return False

    def maybe_oom(self, iteration: int) -> None:
        """Raise one synthetic RESOURCE_EXHAUSTED if armed for this
        iteration (consumed, so the caller's retry proceeds)."""
        if self.take("oom", iteration):
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected device OOM at iteration "
                f"{iteration} (LIGHTGBM_TPU_FAULT_INJECT)")

    def maybe_kill(self, iteration: int) -> None:
        """SIGKILL this process if armed for this iteration — no
        cleanup, no atexit: the hard-crash the checkpoint layer must
        survive."""
        if self.take("kill", iteration):
            os.kill(os.getpid(), signal.SIGKILL)

    # -- distributed kinds (rank-gated; docs/RESILIENCE.md) ------------
    @staticmethod
    def _rank_selected() -> bool:
        """Is THIS process one of the fault-target ranks
        (``LIGHTGBM_TPU_FAULT_RANK``, comma list, default ``0``)? The
        process index is only queried when a distributed kind is
        actually armed, so inert plans never touch jax."""
        targets = {int(r) for r in
                   os.environ.get("LIGHTGBM_TPU_FAULT_RANK",
                                  "0").split(",") if r.strip()}
        try:
            import jax
            me = jax.process_index()
        except Exception:
            me = 0
        return me in targets

    def maybe_distributed_fault(self, iteration: int) -> None:
        """Fire ``rank_kill`` / ``stall_rank`` if armed for this
        iteration and this process is a selected rank. ``rank_kill``
        SIGKILLs (like ``kill``); ``stall_rank`` records a fault event
        and then sleeps forever — the straggler the peers' collective
        watchdog must catch, because no transport error will."""
        if self.fires("rank_kill", iteration) and self._rank_selected():
            self.take("rank_kill", iteration)
            os.kill(os.getpid(), signal.SIGKILL)
        if self.fires("stall_rank", iteration) and self._rank_selected():
            self.take("stall_rank", iteration)
            record_fault_event(
                "stall_rank", iteration=iteration, action="stall",
                detail="injected infinite stall "
                       "(LIGHTGBM_TPU_FAULT_INJECT)")
            while True:
                time.sleep(3600.0)

    @staticmethod
    def _replica_selected() -> bool:
        """Is THIS serve replica a fault target? Serve replicas are
        independent single-process jax runtimes distinguished only by
        the supervisor-exported ``LIGHTGBM_TPU_RANK``, so the gate
        compares that (not ``jax.process_index()``, which is 0 in
        every replica) against ``LIGHTGBM_TPU_FAULT_RANK``."""
        targets = {int(r) for r in
                   os.environ.get("LIGHTGBM_TPU_FAULT_RANK",
                                  "0").split(",") if r.strip()}
        me = int(os.environ.get("LIGHTGBM_TPU_RANK") or 0)
        return me in targets

    def maybe_serve_kill(self, request_count: int) -> None:
        """SIGKILL the serving daemon when armed for this accepted
        request ordinal (and this replica is a selected rank) —
        the mid-traffic replica death the launch supervisor's health
        checks and per-rank restarts must absorb. Fired BEFORE the
        request enters the batcher, so no accepted request is ever
        silently dropped (the dying connection is the client's
        signal to retry)."""
        if self.fires("serve_kill", request_count) \
                and self._replica_selected():
            self.take("serve_kill", request_count)
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_refuse_init(self) -> None:
        """Raise one synthetic connection-refused error per remaining
        ``init_refuse`` budget — the coordinator-not-up failure
        ``init_distributed``'s retry loop must absorb."""
        if self._init_refusals_left > 0:
            self._init_refusals_left -= 1
            record_fault_event(
                "init_refuse", action="retry",
                detail="injected coordinator connection refusal "
                       "(LIGHTGBM_TPU_FAULT_INJECT)")
            raise InjectedInitRefused(
                "connection refused: injected coordinator-not-up "
                "failure (LIGHTGBM_TPU_FAULT_INJECT)")
