# tpulint fixture: TPL005 positive — hash-ordered iteration feeding
# device work.
import jax
import jax.numpy as jnp


def reduce_shards(shards):
    total = jnp.float32(0.0)
    names = {s.name for s in shards}           # a set
    # EXPECT: TPL005
    for name in names:                         # hash order -> psum order
        total = total + jax.lax.psum(shards[name], "x")
    return total


def trace_order(parts):
    keys = set(parts)
    # EXPECT: TPL005
    stacked = jnp.stack([parts[k] for k in keys])   # comprehension
    return stacked


def tied_sort(callbacks):
    cbs = {c for c in callbacks if c.enabled}
    # EXPECT: TPL005
    ordered = sorted(cbs, key=lambda c: c.order)    # ties keep set order
    for c in ordered:
        c(jnp.zeros(()))
    return ordered
