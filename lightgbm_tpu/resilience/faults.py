"""Deterministic fault injection + device-error classification.

The test harness behind every resilience path: the
``LIGHTGBM_TPU_FAULT_INJECT`` environment variable carries a
comma-separated list of ``kind@iteration`` tokens, e.g.::

    LIGHTGBM_TPU_FAULT_INJECT=nan_grad@7,oom@3,kill@12

Kinds:

- ``nan_grad@N`` / ``nan_hess@N`` — poison the iteration-``N`` gradient
  / hessian vectors with NaN before the tree is grown, exercising the
  non-finite guard (``nonfinite_policy``). Inside the fused jitted step
  the poisoning is traced as a ``where(it == N, ...)`` so the program
  stays a single dispatch.
- ``oom@N`` — raise a synthetic ``RESOURCE_EXHAUSTED`` runtime error at
  the iteration-``N`` grow dispatch. The token is *consumed* on firing,
  so the degradation retry path succeeds (one ``oom@N`` = one transient
  OOM). Repeat the token to simulate back-to-back exhaustion.
- ``kill@N`` — ``SIGKILL`` the current process at the *start* of
  iteration ``N``, exercising checkpoint/auto-resume end to end.

A missing / empty variable parses to an inert plan: every query is a
cheap tuple-membership test, nothing touches jax, and production runs
pay nothing.
"""

from __future__ import annotations

import os
import signal
from typing import Dict, List, Tuple

__all__ = ["FaultPlan", "InjectedResourceExhausted", "is_resource_exhausted"]

_KNOWN_KINDS = ("nan_grad", "nan_hess", "oom", "kill")


class InjectedResourceExhausted(RuntimeError):
    """Synthetic stand-in for jaxlib's ``XlaRuntimeError`` OOM: carries
    the same ``RESOURCE_EXHAUSTED`` marker the classifier keys on."""


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA allocation failures (``XlaRuntimeError`` with a
    RESOURCE_EXHAUSTED status, allocator "out of memory" messages) and
    their injected stand-ins. Message-based on purpose: the concrete
    exception class moved across jaxlib versions."""
    msg = str(exc)
    return ("RESOURCE_EXHAUSTED" in msg
            or "Out of memory" in msg
            or "out of memory" in msg)


class FaultPlan:
    """Parsed ``kind@iteration`` schedule with consume-on-fire
    semantics for ``oom`` (so a retry after degradation succeeds)."""

    def __init__(self, spec: str = ""):
        self._events: Dict[str, List[int]] = {}
        for token in (spec or "").split(","):
            token = token.strip()
            if not token:
                continue
            if "@" not in token:
                raise ValueError(
                    f"bad fault-injection token {token!r} "
                    "(expected kind@iteration)")
            kind, it = token.split("@", 1)
            kind = kind.strip()
            if kind not in _KNOWN_KINDS:
                raise ValueError(
                    f"unknown fault-injection kind {kind!r} "
                    f"(known: {', '.join(_KNOWN_KINDS)})")
            self._events.setdefault(kind, []).append(int(it))
        for lst in self._events.values():
            lst.sort()

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(os.environ.get("LIGHTGBM_TPU_FAULT_INJECT", ""))

    @property
    def active(self) -> bool:
        return bool(self._events)

    def iters(self, kind: str) -> Tuple[int, ...]:
        """All scheduled iterations for ``kind`` (non-consuming; the
        fused step bakes these into the traced program)."""
        return tuple(self._events.get(kind, ()))

    def fires(self, kind: str, iteration: int) -> bool:
        """Non-consuming membership test (nan_grad / nan_hess)."""
        return iteration in self._events.get(kind, ())

    def take(self, kind: str, iteration: int) -> bool:
        """Consuming test: True once per scheduled token."""
        lst = self._events.get(kind)
        if lst and iteration in lst:
            lst.remove(iteration)
            return True
        return False

    def maybe_oom(self, iteration: int) -> None:
        """Raise one synthetic RESOURCE_EXHAUSTED if armed for this
        iteration (consumed, so the caller's retry proceeds)."""
        if self.take("oom", iteration):
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected device OOM at iteration "
                f"{iteration} (LIGHTGBM_TPU_FAULT_INJECT)")

    def maybe_kill(self, iteration: int) -> None:
        """SIGKILL this process if armed for this iteration — no
        cleanup, no atexit: the hard-crash the checkpoint layer must
        survive."""
        if self.take("kill", iteration):
            os.kill(os.getpid(), signal.SIGKILL)
