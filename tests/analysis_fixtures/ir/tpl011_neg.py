"""TPL011 negative: a python-float literal routed through ``where``.
Under x64 it appears as a WEAK rank-0 f64 scalar that immediately
``convert_element_type``s down to f32 — benign literal plumbing the
rule exempts (flagging it would mean pinning every scalar literal in
the tree for zero generated-code difference)."""


def build(jax, jnp):
    def fn(x):
        return jnp.where(x > 0.0, x, 0.0)

    return fn, (jnp.ones((4,), jnp.float32),)
