"""Quantized histogram collectives + payload-adaptive parallelism
(lightgbm_tpu/parallel/comms.py, ISSUE 9; docs/COLLECTIVES.md).

Covers the four invariants the subsystem sells:
- the quantized allreduce is REPLICATED (byte-identical on all ranks)
  and close to the exact f32 reduction;
- error feedback keeps ACCUMULATED error bounded across many
  reductions (many trees' worth), instead of compounding;
- the dtype-aware payload model matches both the known MULTICHIP_r04
  expectations and the lowered StableHLO, and the int8 wire really is
  int8 on the exchange path;
- tree_learner=auto picks data-parallel at the narrow Higgs shape,
  voting at the wide Allstate shape, feature at replicable sizes.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - jax>=0.8
    from jax import shard_map

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import comms
from lightgbm_tpu.parallel.mesh import make_mesh, shard_rows

from conftest import make_synthetic_binary

needs_mesh = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs the 8-device mesh")

F, B = 13, 9  # deliberately unaligned with the 256-element block


def _mesh():
    return make_mesh(8)


def _per_rank(fn, *arrays):
    """Run ``fn`` under shard_map returning every rank's result
    stacked on axis 0 (so tests can assert cross-rank byte-equality,
    which the usual replicated out_spec would hide)."""
    mesh = _mesh()
    axis = mesh.axis_names[0]
    sharded = shard_map(lambda *a: fn(axis, *a), mesh=mesh,
                        in_specs=tuple(P(axis) for _ in arrays),
                        out_specs=P(axis), check_rep=False)
    return np.asarray(jax.jit(sharded)(*arrays))


# ---------------------------------------------------------------------
# the primitive
# ---------------------------------------------------------------------

@needs_mesh
@pytest.mark.parametrize("mode", ["int16", "int8"])
@pytest.mark.parametrize("strategy", ["psum", "exchange"])
def test_quantized_allreduce_rank_identical_and_close(mode, strategy):
    rs = np.random.RandomState(0)
    x = rs.randn(8, F, B, 2).astype(np.float32) * 5.0

    def body(axis, xl):
        return comms.hist_allreduce(xl[0], axis, mode,
                                    strategy=strategy)[None]

    out = _per_rank(body, jnp.asarray(x))
    ref = x.sum(axis=0)
    for r in range(1, 8):
        assert np.array_equal(out[r], out[0]), (
            f"rank {r} diverged from rank 0 — split decisions would "
            "deadlock the mesh")
    tol = 2e-4 if mode == "int16" else 2e-2
    assert np.max(np.abs(out[0] - ref)) / np.max(np.abs(ref)) < tol


@needs_mesh
def test_f32_mode_is_exact_psum():
    rs = np.random.RandomState(1)
    x = rs.randn(8, F, B, 2).astype(np.float32)

    def body(axis, xl):
        return comms.hist_allreduce(xl[0], axis, "f32")[None]

    out = _per_rank(body, jnp.asarray(x))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-6,
                               atol=1e-5)


@needs_mesh
def test_int_histograms_fall_back_to_exact_psum():
    """Quantized-gradient training reduces exact int32 histograms —
    the comms layer must never quantize them."""
    rs = np.random.RandomState(2)
    x = rs.randint(-1000, 1000, size=(8, F, B, 2)).astype(np.int32)

    def body(axis, xl):
        return comms.hist_allreduce(xl[0], axis, "int8")[None]

    out = _per_rank(body, jnp.asarray(x))
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out[0], x.sum(axis=0))


@needs_mesh
@pytest.mark.parametrize("mode", ["int8", "int16"])
@pytest.mark.parametrize("strategy", ["psum", "exchange"])
def test_error_feedback_bounds_accumulated_error(mode, strategy):
    """EF telescope: across 10 trees' worth of sequential reductions
    (num_leaves-1 = 6 splits/tree -> 60 rounds) the CUMULATIVE
    dequantization error stays bounded by ~one quantization step,
    where the feedback-free chain compounds. Covers BOTH transports —
    the exchange arm executes the phase-2 requantization-error fold
    into the owner's chunk (comms._allreduce_exchange), not just the
    shared-scale psum path CPU training defaults to."""
    rounds = 60
    rs = np.random.RandomState(3)
    hists = rs.randn(8, rounds, F, B, 2).astype(np.float32)

    def run(use_ef):
        def body(axis, h_seq):
            def step(ef, h):
                if use_ef:
                    y, ef = comms.hist_allreduce(h, axis, mode,
                                                 error_feedback=ef,
                                                 strategy=strategy)
                else:
                    y = comms.hist_allreduce(h, axis, mode,
                                             strategy=strategy)
                return ef, y

            _, ys = lax.scan(step, jnp.zeros((F, B, 2), jnp.float32),
                             h_seq[0])
            return ys[None]

        ys = _per_rank(body, jnp.asarray(hists))[0]
        true = hists.sum(axis=0)
        return np.abs(np.cumsum(ys - true, axis=0)).max(axis=(1, 2, 3))

    err_ef = run(True)
    err_no = run(False)
    # bounded: the running total never exceeds a small multiple of one
    # round's quantization error, and beats the feedback-free chain
    assert err_ef.max() < 0.5 * err_no.max(), (err_ef.max(),
                                               err_no.max())
    assert err_ef[-1] < 3.0 * err_ef[: rounds // 6].max(), (
        "accumulated error kept growing across trees", err_ef)


@needs_mesh
def test_exchange_wire_really_is_int8(monkeypatch):
    """On the exchange strategy the largest collective operand is the
    packed int8 payload — ~4x fewer bytes than the f32 psum it
    replaces (scale sideband included in the measurement)."""
    monkeypatch.setenv("LIGHTGBM_TPU_COMM_EXCHANGE", "1")
    mesh = _mesh()
    axis = mesh.axis_names[0]
    # wide enough that the D*BLOCK padding is negligible next to the
    # payload (the ratio at tiny shapes measures padding, not wire)
    x = jnp.zeros((8, 256, 255, 2), jnp.float32)

    def trace(mode):
        def body(xl):
            return comms.hist_allreduce(xl[0], axis, mode)[None]

        return comms.collective_payloads(
            shard_map(body, mesh=mesh, in_specs=P(axis),
                      out_specs=P(axis), check_rep=False), x)

    max_f32 = max(r["bytes"] for r in trace("f32"))
    recs8 = trace("int8")
    max_i8 = max(r["bytes"] for r in recs8)
    assert any(r["itemsize"] == 1 for r in recs8), recs8
    assert max_f32 / max_i8 > 3.8, (max_f32, max_i8)


# ---------------------------------------------------------------------
# payload model + cost model (the dryrun accounting seed)
# ---------------------------------------------------------------------

def test_payload_model_matches_r04_expectations():
    """MULTICHIP_r04's measured ordering at F=64, B=16, top_k=3:
    full-hist 2048 >> voting 384 >> feature 32 elems."""
    assert comms.payload_elems("data", 64, 16) == 2048
    assert comms.payload_elems("voting", 64, 16, top_k=3) == 384
    assert comms.payload_elems("feature", 64, 16) == 32


@needs_mesh
def test_jaxpr_accounting_reproduces_r04_shape():
    """The dtype-aware walk over the lowered data-parallel grower
    reproduces the model: max collective == the full [F, B, 2] f32
    histogram, in elems AND bytes."""
    from lightgbm_tpu.ops.grow import GrowConfig, grow_tree_impl
    from lightgbm_tpu.ops.split import SplitParams

    fw, bw = 64, 16
    mesh = _mesh()
    axis = mesh.axis_names[0]
    cfg = GrowConfig(num_leaves=7, num_bins=bw,
                     split=SplitParams(min_data_in_leaf=1.0),
                     hist_method="scatter", axis_name=axis)
    n = 64 * 8

    def fn(bins_T, grad, hess, w, fm, fnb, fnan):
        return grow_tree_impl(cfg, bins_T, grad, hess, w, fm, fnb,
                              fnan)

    sh = shard_map(fn, mesh=mesh,
                   in_specs=(P(None, axis), P(axis), P(axis), P(axis),
                             P(), P(), P()),
                   out_specs=(P(), P(axis)), check_rep=False)
    recs = comms.collective_payloads(
        sh, jnp.zeros((fw, n), jnp.uint8), jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32), jnp.ones((n,), jnp.float32),
        jnp.ones((fw,), jnp.bool_), jnp.full((fw,), bw, jnp.int32),
        jnp.full((fw,), -1, jnp.int32))
    assert max(r["elems"] for r in recs) == \
        comms.payload_elems("data", fw, bw) == 2048
    assert max(r["bytes"] for r in recs) == \
        comms.payload_bytes("data", fw, bw, "f32") == 8192


def test_wire_bytes_reduction_at_allstate_shape():
    elems = comms.payload_elems("data", 4228, 255)
    f32b = elems * comms.WIRE_ITEMSIZE["f32"]
    i8b = elems * comms.WIRE_ITEMSIZE["int8"]
    assert f32b / i8b >= 4.0
    assert f32b > 8 * 2 ** 20  # the 8.6 MB per-level reduction


def test_choose_parallel_mode_decision_table():
    # the ISSUE 9 acceptance shapes
    assert comms.choose_parallel_mode(28, 255, 10_500_000, 8) == "data"
    assert comms.choose_parallel_mode(4228, 255, 13_200_000, 8) == \
        "voting"
    # small data replicates -> feature
    assert comms.choose_parallel_mode(4228, 255, 4000, 8) == "feature"
    # voting can't elect fewer features than exist
    assert comms.choose_parallel_mode(30, 255, 10_500_000, 8,
                                      top_k=20) == "data"
    # one device: nothing to shard
    assert comms.choose_parallel_mode(4228, 255, 13_200_000, 1) == \
        "data"
    # a cheaper wire can keep a mid-width shape on exact data-parallel
    assert comms.choose_parallel_mode(900, 255, 10 ** 7, 8,
                                      "f32") == "voting"
    assert comms.choose_parallel_mode(900, 255, 10 ** 7, 8,
                                      "int8") == "data"


def test_resolve_hist_comm_auto():
    assert comms.resolve_hist_comm("auto", 28, 255) == "f32"
    assert comms.resolve_hist_comm("auto", 4228, 255) == "int16"
    assert comms.resolve_hist_comm("int8", 28, 255) == "int8"
    # auto resolves against the ACTIVE mode's payload: voting moves
    # the small elected buffer, so it stays exact f32 at a width
    # where data-parallel would quantize
    assert comms.resolve_hist_comm("auto", 4228, 255,
                                   parallel_mode="voting") == "f32"
    assert comms.resolve_hist_comm("auto", 4228, 255,
                                   parallel_mode="feature") == "f32"


# ---------------------------------------------------------------------
# training end-to-end on the 8-device world
# ---------------------------------------------------------------------

def _train(X, y, rounds=5, callbacks=None, **extra):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds,
                     callbacks=callbacks or [])


@needs_mesh
def test_int16_training_matches_f32_within_eval_tolerance():
    X, y = make_synthetic_binary(n=4000, f=11, seed=7)
    p_f32 = _train(X, y, tree_learner="data").predict(X[:500])
    b = _train(X, y, tree_learner="data", hist_comm="int16")
    assert b._engine.grow_cfg.hist_comm == "int16"
    p_i16 = b.predict(X[:500])
    assert np.max(np.abs(p_i16 - p_f32)) < 1e-3


@needs_mesh
def test_int8_training_runs_and_is_deterministic():
    X, y = make_synthetic_binary(n=4000, f=11, seed=9)
    b1 = _train(X, y, rounds=3, tree_learner="data", hist_comm="int8")
    b2 = _train(X, y, rounds=3, tree_learner="data", hist_comm="int8")
    assert b1.model_to_string() == b2.model_to_string()
    # still learns: better than the 0.5 coin flip
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, b1.predict(X)) > 0.8


@needs_mesh
@pytest.mark.parametrize("grower", ["compact", "masked", "level"])
def test_grower_output_rank_identical_under_int8(grower):
    """The acceptance invariant: every rank's TREE is byte-equal under
    quantized comms (the grower's out_spec normally hides this —
    return each rank's copy explicitly). All three growers thread
    their own EF carry (rolling [F,B,2] for compact/masked, per-leaf
    [L,F,B,2] slots for level) — each must stay replicated."""
    from lightgbm_tpu.ops.grow import GrowConfig, grow_tree_impl
    from lightgbm_tpu.ops.split import SplitParams

    n, f, mb = 64 * 8, 6, 15
    rs = np.random.RandomState(11)
    bins = rs.randint(0, mb, size=(f, n)).astype(np.uint8)
    yv = (bins.astype(np.float32).T @ rs.randn(f).astype(np.float32)
          > 0).astype(np.float32)
    mesh = _mesh()
    axis = mesh.axis_names[0]
    cfg = GrowConfig(num_leaves=7, num_bins=mb,
                     split=SplitParams(min_data_in_leaf=1.0,
                                       min_sum_hessian_in_leaf=1e-6),
                     hist_method="scatter", axis_name=axis,
                     hist_comm="int8", grower=grower)

    def fn(bins_T, grad, hess, w, fm, fnb, fnan):
        tree, _ = grow_tree_impl(cfg, bins_T, grad, hess, w, fm, fnb,
                                 fnan)
        return (tree.num_leaves[None], tree.leaf_value[None],
                tree.split_feature[None], tree.threshold_bin[None])

    sh = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P(), P(),
                  P()),
        out_specs=(P(axis),) * 4, check_rep=False))
    nl, lv, sf, tb = sh(
        jnp.asarray(bins), jnp.asarray(0.5 - yv),
        jnp.full((n,), 0.25, jnp.float32), jnp.ones((n,), jnp.float32),
        jnp.ones((f,), jnp.bool_), jnp.full((f,), mb, jnp.int32),
        jnp.full((f,), -1, jnp.int32))
    for arr in (np.asarray(nl), np.asarray(lv), np.asarray(sf),
                np.asarray(tb)):
        for r in range(1, 8):
            assert np.array_equal(arr[r], arr[0]), "rank divergence"
    assert int(np.asarray(nl)[0]) == 7


@needs_mesh
def test_auto_tree_learner_engine_wiring():
    """tree_learner=auto at a replicable size resolves to the cost
    model's choice and the engine records it."""
    X, y = make_synthetic_binary(n=2000, f=9, seed=5)
    b = _train(X, y, rounds=2, tree_learner="auto")
    eng = b._engine
    assert eng.mesh is not None
    expected = comms.choose_parallel_mode(
        int(eng.bins_T.shape[0]), eng.grow_cfg.num_bins, eng.n,
        int(eng.mesh.devices.size), "f32", eng.grow_cfg.voting_top_k)
    assert eng.grow_cfg.parallel_mode == expected == "feature"


@needs_mesh
@pytest.mark.parametrize("grower", ["level", "masked"])
def test_auto_tree_learner_demotes_to_data_for_noncompact_grower(grower):
    """auto must never hand the level/masked growers a mode they don't
    implement: at this replicable size the cost model says feature,
    but level raises on anything but data-parallel and masked would
    psum D identical replicated histograms (D-times-inflated counts).
    Both demote to data and still train."""
    X, y = make_synthetic_binary(n=2000, f=9, seed=5)
    b = _train(X, y, rounds=2, tree_learner="auto", grower=grower)
    eng = b._engine
    assert eng.mesh is not None
    assert eng.grow_cfg.parallel_mode == "data"
    assert np.isfinite(b.predict(X[:100])).all()


@needs_mesh
def test_telemetry_comm_fields(tmp_path):
    import lightgbm_tpu.callback as cbm
    from lightgbm_tpu.obs.recorder import summarize_events

    path = str(tmp_path / "comm.jsonl")
    X, y = make_synthetic_binary(n=2000, f=9, seed=6)
    _train(X, y, rounds=2, tree_learner="data", hist_comm="int16",
           callbacks=[cbm.telemetry(path)])
    events = [json.loads(ln) for ln in open(path).read().splitlines()]
    iters = [e for e in events if e.get("event") == "iteration"]
    assert len(iters) == 2
    for ev in iters:
        comm = ev["comm"]
        assert comm["hist_comm"] == "int16"
        assert comm["parallel_mode"] == "data"
        assert comm["world"] == 8
        assert comm["payload_bytes"] > 0
    summary = summarize_events(path)
    assert summary["comm_bytes"] == sum(
        e["comm"]["payload_bytes"] for e in iters)


def test_serial_training_has_null_comm(tmp_path):
    import lightgbm_tpu.callback as cbm

    path = str(tmp_path / "serial.jsonl")
    X, y = make_synthetic_binary(n=600, f=5, seed=8)
    _train(X, y, rounds=1, callbacks=[cbm.telemetry(path)])
    events = [json.loads(ln) for ln in open(path).read().splitlines()
              if ln]
    # compile events (obs/cost.py) legally precede the iteration line
    ev = next(e for e in events if e["event"] == "iteration")
    assert "comm" in ev and ev["comm"] is None
