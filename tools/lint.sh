#!/usr/bin/env sh
# One-shot tpulint runner: analyzer + baseline check. Exits non-zero on
# any non-baselined finding AND on stale/unjustified baseline entries
# (--strict), so CI catches both new hazards and rotted acceptances.
# No jax import happens on this path — safe for backend-less runners.
# Pre-commit loop: `tools/lint.sh --changed` lints only files differing
# from HEAD (~100 ms when nothing in scope changed).
#
# IR stage: `tools/lint.sh --ir` additionally lowers every
# register_jit entry point (CPU, lowering only — works on hosts with
# no TPU) and checks TPL011-TPL014 against tools/ir_budgets.json. The
# stage is pinned to the CPU backend and fenced by a wall-clock budget
# (LINT_IR_TIMEOUT seconds, default 90; the full table lowers in ~10s)
# so a pathological trace can never hang CI.
#
# The default (jax-free) stage also runs the contract pass
# TPL015-TPL018 against the obs/schemas.py registries and verifies the
# generated docs/OBSERVABILITY.md tables haven't drifted from them
# (tools/gen_obs_docs.py --check; regenerate with --write). It is
# fenced by LINT_TIMEOUT seconds (default 60; a full run takes ~7s).
set -eu
cd "$(dirname "$0")/.."
for arg in "$@"; do
    if [ "$arg" = "--ir" ]; then
        JAX_PLATFORMS=cpu
        export JAX_PLATFORMS
        exec timeout -k 10 "${LINT_IR_TIMEOUT:-90}" \
            python -m lightgbm_tpu lint --strict \
            --baseline tools/tpulint_baseline.txt "$@"
    fi
done
python tools/gen_obs_docs.py --check
exec timeout -k 10 "${LINT_TIMEOUT:-60}" \
    python -m lightgbm_tpu lint --strict \
    --baseline tools/tpulint_baseline.txt "$@"
