"""``python -m lightgbm_tpu pipeline``: the closed production loop.

One supervised lifecycle joins every subsystem the ROADMAP grew
(docs/PIPELINE.md):

    ingest -> train -> publish -> serve -> (fresh data) -> retrain ...

- **Generations**: each generation ingests a fresh (drifting) data
  slice through the PR-7 chunk sources, warm-starts from the previous
  published model (``--warm-start refit`` re-derives the existing
  forest's leaf values from fresh gradients — the reference's
  ``FitByExistingTree`` semantics — then appends; ``append`` continues
  training via ``init_model``; ``none`` retrains from scratch), and
  publishes the result atomically (resilience/publisher.py:
  manifest-first, sha256-validated, retried with jittered backoff)
  into the serve fleet's watch directory.
- **Training runs supervised**: every generation trains under the
  elastic supervisor (resilience/elastic.py) with a per-generation
  checkpoint directory, so a ``rank_kill`` mid-retrain relaunches and
  resumes instead of losing the generation.
- **Serving runs supervised**: the replica fleet runs under
  ``launch --health-port`` (per-rank restart + JSON ping health
  checks); hot swaps ride the daemon's watch-dir poller, which
  validates every managed artifact against its manifest and skips
  torn publications with a ``swap_failure`` fault event.
- **Traffic**: a built-in load generator drives the fleet for the
  whole run and records client-side QPS / latency / shed / error
  continuity into the pipeline's JSONL telemetry — the proof that
  swaps, replica kills and torn publishes never broke the service.
  ``--spike-rate`` turns it into the autoscaling chaos drill: a
  timed load spike the fleet supervisor scales up into
  (``--max-replicas``, resilience/autoscale.py) and back down out of
  (graceful drain — retiring replicas answer ``{"error":
  "draining"}`` and the generator fails over).
- **Canary-gated rollout**: with ``--canary-rows`` each publication
  embeds validation rows + expected raw scores; every replica scores
  them through its real compiled forest BEFORE swapping, refuses a
  mismatching (poisoned) publication, and the fleet supervisor rolls
  the publication back to last-known-good — the pipeline counts that
  as service success (``rollbacks`` in the summary), not a failure.

This module's CLI dispatch, the supervisor loop and the load
generator are jax-free (like ``lint`` / ``launch``): jax loads only
inside the spawned training workers and serve replicas. The hidden
``--train-worker`` mode is that worker entry point.

Threading contract (tpulint TPL006/TPL008 over pipeline.py): the load
generator's stats are shared between its worker thread and the
supervisor loop — every mutable field is touched only under
``self._lock``, and the blocking socket I/O runs outside it.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .utils.log import log_info, log_warning

__all__ = ["main", "build_parser", "LoadGenerator", "replica_stats"]

#: fault kinds routed to the serve fleet's environment; everything
#: else goes to the training workers (docs/PIPELINE.md chaos matrix)
_SERVE_FAULT_KINDS = ("serve_kill",)


# ---------------------------------------------------------------------
# small jax-free protocol clients (supervisor side)
# ---------------------------------------------------------------------

def _rpc(port: int, obj: Dict[str, Any], timeout: float = 10.0,
         host: str = "127.0.0.1") -> Optional[Dict[str, Any]]:
    """One request -> one reply against a serve replica; None on any
    transport/parse failure (the supervisor polls, it never crashes).
    One implementation, shared with the fleet supervisor's health
    probe."""
    from .resilience.elastic import replica_rpc
    return replica_rpc(port, obj, timeout=timeout, host=host)


def replica_stats(port: int, timeout: float = 10.0
                  ) -> Optional[Dict[str, Any]]:
    return _rpc(port, {"cmd": "stats"}, timeout=timeout)


def _split_faults(spec: str) -> Tuple[str, str]:
    """Route a LIGHTGBM_TPU_FAULT_INJECT spec to its side of the
    lifecycle: (train_spec, serve_spec)."""
    train_toks: List[str] = []
    serve_toks: List[str] = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        kind = tok.split("@", 1)[0].strip()
        (serve_toks if kind in _SERVE_FAULT_KINDS
         else train_toks).append(tok)
    return ",".join(train_toks), ",".join(serve_toks)


# ---------------------------------------------------------------------
# telemetry writer (supervisor side, shared by the loadgen thread)
# ---------------------------------------------------------------------

class _EventLog:
    """Append-only JSONL writer shared between the supervisor loop and
    the load-generator thread; the file handle is the shared state,
    one lock orders the writes."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        self._file = open(path, "a", encoding="utf-8")

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event) + "\n"
        with self._lock:
            if self._file is None:
                return
            try:
                self._file.write(line)
                self._file.flush()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            fh, self._file = self._file, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass


# ---------------------------------------------------------------------
# load generator (supervisor side; jax-free)
# ---------------------------------------------------------------------

class LoadGenerator:
    """Constant-rate request driver for the serve fleet.

    One worker thread round-robins the replica ports, keeps one
    persistent connection per replica (reconnecting on failure), and
    classifies every outcome: ``ok``, ``shed`` (typed overload reply),
    ``overloaded`` (hard backpressure), ``draining`` (typed graceful-
    shutdown refusal from a retiring replica — the client's cue to
    fail over, never a dropped request), ``error`` (error reply),
    ``conn`` (connect/reset — a killed or not-yet-scaled-up replica),
    ``timeout`` (a reply that never came: the one class that would
    mean a silently dropped accepted request). A port that failed to
    connect is skipped for a short backoff so traffic concentrates on
    live replicas (an autoscaled fleet has ports that are legitimately
    down). The request rate is adjustable mid-run (``set_rate`` — the
    pipeline's load-spike driver), so it lives under ``self._lock``
    with the stats; all socket I/O happens outside it
    (TPL006/TPL008).
    """

    #: seconds a port sits out after a failed connect (worker-local)
    DEAD_PORT_BACKOFF_SEC = 1.0

    def __init__(self, ports: List[int], n_features: int,
                 rate_per_sec: float = 20.0, rows_per_request: int = 4,
                 reply_timeout: float = 30.0,
                 event_log: Optional[_EventLog] = None,
                 stats_interval: float = 1.0,
                 trace_every: int = 0):
        self.ports = list(ports)
        self.n_features = int(n_features)
        self.rows = max(1, int(rows_per_request))
        self.reply_timeout = float(reply_timeout)
        self.event_log = event_log
        self.stats_interval = max(0.1, float(stats_interval))
        # distributed tracing (obs/trace.py): every Nth request
        # originates a trace — its {"trace": ...} protocol field makes
        # the replica emit queue-wait/batch-window/dispatch spans, and
        # the client-side span lands in the pipeline's own event log
        self.trace_every = max(0, int(trace_every))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._rate = max(0.1, float(rate_per_sec))
        self._counts = {"attempts": 0, "ok": 0, "shed": 0,
                        "overloaded": 0, "draining": 0, "error": 0,
                        "conn": 0, "timeout": 0}
        self._latencies: deque = deque(maxlen=4096)
        self._last_ok: Optional[float] = None
        self._max_ok_gap = 0.0
        self._last_model: Optional[str] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="lightgbm-tpu-pipeline-loadgen")

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)

    def set_rate(self, rate_per_sec: float) -> None:
        """Retarget the request rate mid-run (the load-spike driver);
        the worker picks it up on its next period."""
        with self._lock:
            self._rate = max(0.1, float(rate_per_sec))

    def rate(self) -> float:
        with self._lock:
            return self._rate

    def _note(self, outcome: str, latency: Optional[float] = None,
              model: Optional[str] = None,
              want_stats: bool = False) -> Optional[Dict[str, Any]]:
        """Record one outcome; with ``want_stats`` also returns the
        event-ready stats view, so the worker thread never has to read
        the shared fields outside this one locked section
        (``snapshot()`` below is the supervisor-thread reader of the
        same state). The view — a sort of the latency window — is only
        built on the event cadence, not per request."""
        now = time.monotonic()
        with self._lock:
            self._counts["attempts"] += 1
            self._counts[outcome] += 1
            if latency is not None:
                self._latencies.append(latency)
            if outcome == "ok":
                if self._last_ok is not None:
                    self._max_ok_gap = max(self._max_ok_gap,
                                           now - self._last_ok)
                self._last_ok = now
                if model is not None:
                    self._last_model = model
            if not want_stats:
                return None
            counts = dict(self._counts)
            lat = sorted(self._latencies)
            gap = self._max_ok_gap
            last_ok = self._last_ok
            model_now = self._last_model
        return self._format(counts, lat, gap, last_ok, model_now)

    @staticmethod
    def _format(counts: Dict[str, int], lat: List[float], gap: float,
                last_ok: Optional[float],
                model: Optional[str]) -> Dict[str, Any]:
        out: Dict[str, Any] = {**counts, "max_ok_gap_s": round(gap, 3),
                               "model": model}
        if last_ok is not None:
            out["since_last_ok_s"] = round(
                time.monotonic() - last_ok, 3)
        if lat:
            out["p50_ms"] = round(lat[len(lat) // 2] * 1e3, 3)
            out["p99_ms"] = round(
                lat[min(len(lat) - 1, (len(lat) * 99) // 100)] * 1e3, 3)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Supervisor-side stats view (the summary + swap gating)."""
        with self._lock:
            counts = dict(self._counts)
            lat = sorted(self._latencies)
            gap = self._max_ok_gap
            last_ok = self._last_ok
            model = self._last_model
        return self._format(counts, lat, gap, last_ok, model)

    # -- worker thread -------------------------------------------------
    def _run(self) -> None:
        import random as _random
        rng = _random.Random(1234)
        conns: Dict[int, Any] = {}
        # worker-local failover state: a port that refused a connect
        # sits out a short backoff so traffic concentrates on live
        # replicas (only this thread reads/writes it — no lock)
        dead_until: Dict[int, float] = {}
        next_stats = time.monotonic() + self.stats_interval
        i = 0
        while True:
            with self._lock:
                period = 1.0 / self._rate
            if self._stop.wait(period):
                break
            now = time.monotonic()
            port = None
            for _ in range(len(self.ports)):
                candidate = self.ports[i % len(self.ports)]
                i += 1
                if dead_until.get(candidate, 0.0) <= now:
                    port = candidate
                    break
            if port is None:                 # every port backing off:
                port = self.ports[i % len(self.ports)]
                i += 1                       # probe one anyway
            rows = [[rng.uniform(-2.0, 2.0)
                     for _ in range(self.n_features)]
                    for _ in range(self.rows)]
            payload: Dict[str, Any] = {"rows": rows}
            span_ctx = None
            if self.trace_every and self.event_log is not None \
                    and (i - 1) % self.trace_every == 0:
                from .obs import trace as _trace
                span_ctx = (_trace.new_trace_id(),
                            _trace.new_span_id(),
                            time.perf_counter())
                payload["trace"] = {"trace_id": span_ctx[0],
                                    "span_id": span_ctx[1]}
            t0 = time.monotonic()
            want = self.event_log is not None and t0 >= next_stats
            try:
                fh = conns.get(port)
                if fh is None:
                    s = socket.create_connection(
                        ("127.0.0.1", port), timeout=5.0)
                    s.settimeout(self.reply_timeout)
                    fh = s.makefile("rw", encoding="utf-8")
                    conns[port] = fh
                fh.write(json.dumps(payload) + "\n")
                fh.flush()
                line = fh.readline()
                if not line:
                    raise OSError("connection closed by replica")
                reply = json.loads(line)
            except socket.timeout:
                conns.pop(port, None)
                stats = self._note("timeout", want_stats=want)
            except (OSError, ValueError):
                conns.pop(port, None)
                dead_until[port] = (time.monotonic()
                                    + self.DEAD_PORT_BACKOFF_SEC)
                stats = self._note("conn", want_stats=want)
            else:
                dt = time.monotonic() - t0
                if reply.get("draining"):
                    # typed graceful-shutdown refusal: fail over now —
                    # the replica is retiring and will close
                    conns.pop(port, None)
                    dead_until[port] = (time.monotonic()
                                        + self.DEAD_PORT_BACKOFF_SEC)
                    stats = self._note("draining", want_stats=want)
                elif reply.get("shed"):
                    stats = self._note("shed", want_stats=want)
                elif reply.get("overloaded"):
                    stats = self._note("overloaded", want_stats=want)
                elif "error" in reply:
                    stats = self._note("error", want_stats=want)
                else:
                    stats = self._note("ok", latency=dt,
                                       model=reply.get("model"),
                                       want_stats=want)
                    if span_ctx is not None:
                        # the root client-side span: written straight
                        # to the supervisor's event log (no recorder
                        # drains on this side); the replica's
                        # serve/request span parents to it
                        from .obs import trace as _trace
                        self.event_log.write(_trace.make_span(
                            "client/request", span_ctx[2],
                            trace_id=span_ctx[0],
                            span_id=span_ctx[1],
                            attrs={"model": reply.get("model"),
                                   "port": port,
                                   "outcome": "ok"}))
            if stats is not None:
                next_stats = time.monotonic() + self.stats_interval
                self.event_log.write(
                    {"event": "client", "time": time.time(), **stats})
        for fh in conns.values():
            try:
                fh.close()
            except OSError:
                pass


def _drive_spike(loadgen: "LoadGenerator", events: _EventLog,
                 base_rate: float, spike_rate: float,
                 start_sec: float, duration_sec: float,
                 stop: threading.Event) -> None:
    """One load spike: wait, jump the request rate, hold, fall back —
    the traffic shape the autoscaling chaos drill scales up into and
    back down out of. Runs on its own daemon thread; the stop event
    aborts the wait phases but the rate is ALWAYS restored."""
    if stop.wait(max(0.0, float(start_sec))):
        return
    loadgen.set_rate(spike_rate)
    events.write({"event": "pipeline", "phase": "spike_start",
                  "rate": float(spike_rate), "time": time.time()})
    stop.wait(max(0.0, float(duration_sec)))
    loadgen.set_rate(base_rate)
    events.write({"event": "pipeline", "phase": "spike_end",
                  "rate": float(base_rate), "time": time.time()})


class _ClientMetrics:
    """Bridges the load generator's client-side view into the
    supervisor's /metrics endpoint (obs/export.py extra families).

    The endpoint starts before the load generator exists (the
    bootstrap generation trains first), so the provider holds a slot
    the supervisor fills later; the slot is written by the supervisor
    thread and read by HTTP scrape threads, so both sides go through
    ``self._lock`` (TPL008). The snapshot itself runs outside the
    slot lock — the generator locks its own stats."""

    def __init__(self):
        self._lock = threading.Lock()
        self._loadgen: Optional[LoadGenerator] = None

    def attach(self, loadgen: "LoadGenerator") -> None:
        with self._lock:
            self._loadgen = loadgen

    def families(self) -> Dict[str, Any]:
        with self._lock:
            loadgen = self._loadgen
        if loadgen is None:
            return {}
        from .obs.export import counter_family, gauge_family
        snap = loadgen.snapshot()
        fams: Dict[str, Any] = {}
        for key in ("attempts", "ok", "shed", "overloaded",
                    "draining", "error", "conn", "timeout"):
            fams[f"client_{key}"] = counter_family(snap.get(key, 0))
        for key in ("p50_ms", "p99_ms", "max_ok_gap_s",
                    "since_last_ok_s"):
            if snap.get(key) is not None:
                fams[f"client_{key}"] = gauge_family(snap[key])
        return fams


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------

_HELP_EPILOG = """\
The pipeline drives ingest -> train -> publish -> serve generations
under supervision (docs/PIPELINE.md): training generations run under
the elastic supervisor with per-generation checkpoint auto-resume,
models publish atomically (manifest-first, sha256-validated, retried
with backoff) into the serve fleet's watch directory, and the fleet
runs under `launch --health-port` with per-replica restarts, replica
autoscaling (--max-replicas) and canary-gated rollout with automatic
rollback (--canary-rows / --rollback-grace). Chaos rides
LIGHTGBM_TPU_FAULT_INJECT / --fault-inject: serve_kill@N goes to the
fleet, everything else (rank_kill@I, publish_torn@G, store_outage@G,
publish_poison@G, refit_nan@T, nan_grad@I, ...) to the training
workers.

exit codes:
  0  every generation trained, published, and was confirmed serving
  1  a generation failed, publication failed, or the fleet never
     confirmed the final model
  2  bad command line
"""


def build_parser() -> argparse.ArgumentParser:
    from .config import Config
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu pipeline",
        description="Continuous train -> publish -> serve lifecycle "
                    "under supervision: warm-start retraining on "
                    "fresh data, atomic manifest-validated "
                    "publication, health-checked serve fleet, "
                    "built-in load generator.",
        epilog=_HELP_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--workdir", required=True,
                   help="working directory (publish/, checkpoints/, "
                        "telemetry/, logs/ are created inside)")
    p.add_argument("--generations", type=int, default=3,
                   help="retrain generations to run (default 3)")
    p.add_argument("--rounds", type=int, default=10,
                   help="boosting iterations added per generation")
    p.add_argument("--rows", type=int, default=4000,
                   help="rows of fresh data per generation")
    p.add_argument("--features", type=int, default=16,
                   help="feature count of the synthetic stream")
    p.add_argument("--num-leaves", type=int, default=15)
    p.add_argument("--warm-start",
                   choices=("append", "refit", "none"),
                   default="append",
                   help="how generation g>0 uses generation g-1's "
                        "published model: append = continued training "
                        "(init_model); refit = re-derive the existing "
                        "forest's leaf values from fresh gradients "
                        "(FitByExistingTree semantics) then append; "
                        "none = from scratch")
    p.add_argument("--refit-decay", type=float, default=0.9,
                   help="refit decay rate (new_leaf = decay*old + "
                        "(1-decay)*fit)")
    p.add_argument("--ingest-chunk-rows", type=int, default=512,
                   help="streaming ingest chunk size (data/, PR 7 "
                        "chunk sources)")
    p.add_argument("--param", action="append", default=[],
                   metavar="K=V",
                   help="extra training parameter (repeatable), e.g. "
                        "--param nonfinite_policy=skip_tree")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve replicas under the health-checked "
                        "fleet supervisor")
    p.add_argument("--port", type=int, default=0,
                   help="base serve port (default: a free port)")
    p.add_argument("--request-rate", type=float, default=20.0,
                   help="load-generator requests per second (0 "
                        "disables the load generator)")
    p.add_argument("--request-rows", type=int, default=4,
                   help="rows per generated request")
    p.add_argument("--spike-rate", type=float, default=0.0,
                   help="load-spike request rate: after --spike-start "
                        "seconds the load generator jumps to this "
                        "rate for --spike-duration seconds, then "
                        "falls back (0 = no spike; the autoscaling "
                        "chaos drill)")
    p.add_argument("--spike-start", type=float, default=5.0,
                   help="seconds after the fleet is ready before the "
                        "load spike begins")
    p.add_argument("--spike-duration", type=float, default=10.0,
                   help="seconds the load spike lasts")
    p.add_argument("--max-replicas", type=int,
                   default=Config.serve_max_replicas,
                   help="replica autoscaling ceiling: the fleet "
                        "supervisor spawns replicas up to this count "
                        "on load and retires them (graceful drain) "
                        "when it subsides (0 = fixed fleet)")
    p.add_argument("--min-replicas", type=int, default=0,
                   help="autoscaling floor (default: --replicas)")
    p.add_argument("--autoscale-up-qps", type=float,
                   default=Config.autoscale_up_qps,
                   help="scale up when fleet QPS exceeds this per "
                        "active replica")
    p.add_argument("--autoscale-down-qps", type=float,
                   default=Config.autoscale_down_qps,
                   help="scale down when fleet QPS would stay under "
                        "this per replica with one replica fewer "
                        "(hysteresis: keep it below --autoscale-up-"
                        "qps)")
    p.add_argument("--autoscale-up-p99-ms", type=float,
                   default=Config.autoscale_up_p99_ms,
                   help="scale up when any replica's p99 exceeds "
                        "this (0 = QPS/shed signals only)")
    p.add_argument("--retire-grace", type=float, default=10.0,
                   help="seconds a scaled-down replica gets to drain "
                        "in-flight requests before a hard kill")
    p.add_argument("--rollback-grace", type=float, default=6.0,
                   help="seconds the fleet supervisor waits for some "
                        "replica to adopt a new publication before a "
                        "canary-refused one is rolled back")
    p.add_argument("--publish-keep", type=int,
                   default=Config.publish_keep,
                   help="retention: prune publications beyond the N "
                        "newest valid manifests after each publish "
                        "(0 = keep everything; the served and last-"
                        "known-good models are never pruned)")
    p.add_argument("--canary-rows", type=int,
                   default=Config.canary_rows,
                   help="validation rows embedded in each publication "
                        "manifest; replicas score them through the "
                        "real compiled forest BEFORE swapping and "
                        "refuse on mismatch (0 = no canary gate)")
    p.add_argument("--canary-tol", type=float,
                   default=Config.canary_tol,
                   help="absolute tolerance for canary raw-score "
                        "agreement")
    p.add_argument("--trace-every", type=int,
                   default=Config.trace_sample_every,
                   help="originate a distributed trace on every Nth "
                        "load-generator request: the replica answers "
                        "with queue-wait/batch-window/dispatch spans "
                        "joined by `python -m lightgbm_tpu trace` "
                        "(0 disables trace sampling)")
    p.add_argument("--max-restarts", type=int, default=6,
                   help="restart budget for each supervised side")
    p.add_argument("--max-restarts-per-window", type=int, default=0,
                   help="sliding-window restart cap (0 = disabled)")
    p.add_argument("--restart-window", type=float, default=300.0)
    p.add_argument("--grace", type=float, default=5.0,
                   help="teardown grace seconds")
    p.add_argument("--health-interval", type=float, default=1.0)
    p.add_argument("--health-grace", type=float, default=90.0,
                   help="startup window before a replica is pinged")
    p.add_argument("--swap-timeout", type=float, default=180.0,
                   help="seconds to wait for the fleet to confirm a "
                        "published model before failing")
    p.add_argument("--shed-queue-rows", type=int,
                   default=Config.serve_shed_queue_rows)
    p.add_argument("--shed-p99-ms", type=float,
                   default=Config.serve_shed_p99_ms)
    p.add_argument("--metrics-port", type=int,
                   default=Config.metrics_port,
                   help="base port of the fleet metrics plane "
                        "(docs/OBSERVABILITY.md): the pipeline "
                        "supervisor's own jax-free OpenMetrics "
                        "/metrics (loadgen client view + supervisor "
                        "counters) binds here, trainer ranks at +1, "
                        "the fleet supervisor at +2 and serve "
                        "replicas at +3+rank (0 = disabled)")
    p.add_argument("--scrape-interval", type=float,
                   default=Config.metrics_scrape_interval_sec,
                   help="seconds between fleet scrapes: the fleet "
                        "supervisor polls per-replica QPS/p99/shed/"
                        "restarts into {\"event\": \"fleet\"} records "
                        "(telemetry/serve.jsonl.fleet) and the "
                        "training supervisor records per-rank "
                        "iteration skew (0 = disabled)")
    p.add_argument("--fault-inject", default=None,
                   help="chaos spec (default: "
                        "$LIGHTGBM_TPU_FAULT_INJECT)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--keep-fleet", action="store_true",
                   help="leave the serve fleet running on exit "
                        "(default: graceful shutdown)")
    # hidden: the jax-side training worker entry point (one
    # generation), spawned under the elastic supervisor
    p.add_argument("--train-worker", type=int, default=None,
                   metavar="GEN", help=argparse.SUPPRESS)
    return p


def _parse_params(pairs: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise ValueError(f"--param expects K=V, got {pair!r}")
        k, v = pair.split("=", 1)
        out[k.strip()] = v.strip()
    return out


# ---------------------------------------------------------------------
# the training worker (jax side; one generation)
# ---------------------------------------------------------------------

def _gen_data(seed: int, gen: int, rows: int, features: int):
    """Drifting synthetic binary stream: generation g's data comes
    from a slowly rotating weight vector, so retraining on fresh data
    genuinely moves the model (and a stale model measurably decays)."""
    import numpy as np
    rng = np.random.RandomState(seed * 1000 + gen)
    w = np.sin(np.arange(features) * 0.7 + 0.35 * gen)
    X = rng.randn(rows, features).astype(np.float64)
    logits = X @ w + 0.5 * rng.randn(rows)
    y = (logits > 0).astype(np.float64)
    return X, y


def _auc(y, scores) -> float:
    """Rank-based AUC without sklearn."""
    import numpy as np
    y = np.asarray(y).ravel()
    s = np.asarray(scores, np.float64).ravel()
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    # average ties
    sorted_s = s[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + j) + 1
        i = j + 1
    npos = float((y > 0).sum())
    nneg = float(len(y) - npos)
    if npos == 0 or nneg == 0:
        return 0.5
    return float((ranks[y > 0].sum() - npos * (npos + 1) / 2)
                 / (npos * nneg))


def _train_worker(args) -> int:
    """One supervised retrain generation: ingest fresh chunked data,
    warm-start from the newest published model, train, publish
    atomically. Runs under the elastic supervisor with
    LIGHTGBM_TPU_CHECKPOINT pointing at the generation's checkpoint
    directory, so a mid-train kill relaunches this exact function and
    resumes."""
    gen = int(args.train_worker)
    import numpy as np

    import lightgbm_tpu as lgb
    from .config import Config
    from .data.sources import GeneratorChunkSource
    from .resilience.publisher import latest_manifest, publish_model

    workdir = os.path.abspath(args.workdir)
    publish_dir = os.path.join(workdir, "publish")
    os.makedirs(publish_dir, exist_ok=True)
    X, y = _gen_data(args.seed, gen, args.rows, args.features)
    chunk = max(64, int(args.ingest_chunk_rows))

    def factory():
        for lo in range(0, len(y), chunk):
            yield X[lo:lo + chunk], y[lo:lo + chunk]

    source = GeneratorChunkSource(factory, num_rows=len(y),
                                  num_features=args.features)
    params: Dict[str, Any] = {
        "objective": "binary", "num_leaves": int(args.num_leaves),
        "verbosity": -1, "ingest_chunk_rows": chunk,
        **_parse_params(args.param)}
    ds = lgb.Dataset(source, params=params)

    init_model = None
    refit_auc = None
    prev = None if (gen == 0 or args.warm_start == "none") \
        else latest_manifest(publish_dir)
    if prev is not None:
        prev_path, prev_manifest = prev
        log_info(f"pipeline[g{gen}]: warm-starting from "
                 f"{prev_path} (generation "
                 f"{prev_manifest.get('generation')})")
        base = lgb.Booster(model_file=prev_path)
        if args.warm_start == "refit":
            # FitByExistingTree: same structures, leaf values
            # re-derived from THIS generation's gradients
            base = base.refit(X, y, decay_rate=args.refit_decay)
            refit_auc = _auc(y, base.predict(X))
            log_info(f"pipeline[g{gen}]: refit AUC on fresh data "
                     f"{refit_auc:.4f}")
        init_model = base
    bst = lgb.train(params, ds, num_boost_round=int(args.rounds),
                    init_model=init_model)
    train_auc = _auc(y, bst.predict(X))
    digest = getattr(ds, "_data_digest", None)
    cfg = Config.from_params(params)
    canary = None
    if int(args.canary_rows) > 0:
        # the serve-side validation batch (docs/SERVING.md): rows are
        # rounded to float32 first — the daemon feeds float32 to the
        # compiled forest, and tree thresholds must see the SAME
        # values here, or a split on the rounding gap would flip a
        # leaf and fail a perfectly good canary
        c_rng = np.random.RandomState(args.seed * 1000 + gen + 777)
        c_rows = c_rng.uniform(
            -2.0, 2.0,
            size=(int(args.canary_rows), int(args.features))
        ).astype(np.float32)
        c_scores = np.asarray(
            bst.predict(c_rows.astype(np.float64), raw_score=True),
            np.float64).reshape(-1)
        canary = {"rows": [[float(v) for v in row] for row in c_rows],
                  "scores": [float(s) for s in c_scores],
                  "tol": float(args.canary_tol)}
    # retention never prunes what the fleet still depends on: the
    # warm-start source (the currently-served / rollback target)
    protect = (prev[1]["sha256"],) \
        if prev is not None and prev[1].get("sha256") else ()
    manifest = publish_model(
        bst, publish_dir, f"model_g{gen:04d}.txt",
        canary=canary, keep=int(args.publish_keep),
        protect_shas=protect,
        metadata={
            "generation": gen,
            "train_auc": round(train_auc, 6),
            "refit_auc": None if refit_auc is None
            else round(refit_auc, 6),
            "data_digest": digest,
            "rounds": int(args.rounds),
            "num_trees": bst.num_trees(),
            "warm_start": args.warm_start if gen else "none",
        },
        retries=cfg.publish_retries,
        backoff_base_sec=cfg.publish_backoff_sec,
        fault_iteration=gen)
    # one {"event": "publish"} JSONL line rides the generation's
    # training telemetry (the recorder closed when train() returned;
    # appends to the same stream keep one post-mortem timeline)
    telem = os.environ.get("LIGHTGBM_TPU_TELEMETRY")
    if telem:
        try:
            # the publish span was recorded AFTER the recorder closed
            # (train() returned before publish_model ran): drain it —
            # and anything else pending — into the same stream
            from .obs.trace import drain_span_events
            spans = drain_span_events()
        except Exception:
            spans = []
        try:
            # fault events taken during publish (store_outage /
            # publish_torn / publish_poison retries) land on the
            # process-level log after the recorder closed — drain them
            # here or the post-mortem loses the retry evidence
            from .resilience.faults import FAULT_EVENTS, drain_events
            faults = drain_events(FAULT_EVENTS)
        except Exception:
            faults = []
        try:
            with open(telem, "a", encoding="utf-8") as fh:
                for ev in spans:
                    fh.write(json.dumps(ev) + "\n")
                for ev in faults:
                    fh.write(json.dumps(ev) + "\n")
                fh.write(json.dumps(
                    {"event": "publish", **manifest}) + "\n")
        except OSError:
            pass
    print(json.dumps({"event": "published", "generation": gen,
                      "file": manifest["file"],
                      "sha256": manifest["sha256"],
                      "train_auc": manifest["train_auc"]}),
          flush=True)
    return 0


# ---------------------------------------------------------------------
# the supervisor (jax-free)
# ---------------------------------------------------------------------

def _worker_cmd(args, gen: int) -> List[str]:
    cmd = [sys.executable, "-m", "lightgbm_tpu", "pipeline",
           "--workdir", args.workdir, "--train-worker", str(gen),
           "--rounds", str(args.rounds), "--rows", str(args.rows),
           "--features", str(args.features),
           "--num-leaves", str(args.num_leaves),
           "--warm-start", args.warm_start,
           "--refit-decay", str(args.refit_decay),
           "--ingest-chunk-rows", str(args.ingest_chunk_rows),
           "--publish-keep", str(args.publish_keep),
           "--canary-rows", str(args.canary_rows),
           "--canary-tol", str(args.canary_tol),
           "--seed", str(args.seed)]
    for pair in args.param:
        cmd += ["--param", pair]
    return cmd


def _train_generation(args, gen: int, dirs: Dict[str, str],
                      train_faults: str, events: _EventLog) -> int:
    """One generation under the elastic supervisor (in-process call —
    elastic.supervise is jax-free)."""
    from .obs import trace as _trace
    from .resilience.elastic import supervise
    env = dict(os.environ)
    env["LIGHTGBM_TPU_CHECKPOINT"] = os.path.join(
        dirs["checkpoints"], f"g{gen:04d}")
    env["LIGHTGBM_TPU_TELEMETRY"] = os.path.join(
        dirs["telemetry"], f"train_g{gen:04d}.jsonl")
    # the generation's trace originates HERE: the workers inherit the
    # context through the env var, so their iteration spans and the
    # publisher's publish span (stamped into the manifest, picked up
    # by the serve watchers) all join this one trace
    trace_id, span_id = _trace.new_trace_id(), _trace.new_span_id()
    env[_trace.TRACE_CTX_ENV] = _trace.format_context(trace_id,
                                                      span_id)
    # also the supervisor's OWN current context while this generation
    # runs: the elastic supervisor's restart/world spans join it
    _trace.set_current_trace(trace_id, span_id)
    if train_faults:
        env["LIGHTGBM_TPU_FAULT_INJECT"] = train_faults
    else:
        env.pop("LIGHTGBM_TPU_FAULT_INJECT", None)
    events.write({"event": "pipeline", "phase": "train_start",
                  "generation": gen, "trace_id": trace_id,
                  "time": time.time()})
    t0 = time.perf_counter()
    rc = supervise(
        1, _worker_cmd(args, gen), max_restarts=args.max_restarts,
        # per-generation log dir: the fleet supervisor writes the
        # same elastic_g*_rank*.log names into ITS dir
        log_dir=os.path.join(dirs["logs"], f"train_g{gen:04d}"),
        grace=args.grace, env=env,
        max_restarts_per_window=args.max_restarts_per_window,
        restart_window_sec=args.restart_window,
        # metrics plane: trainer rank endpoints bind metrics_port+1+r
        # (supervise exports the env var); its fleet events (per-rank
        # iteration skew) land next to the generation's telemetry
        metrics_port=args.metrics_port or None,
        scrape_interval=args.scrape_interval
        if args.metrics_port else 0.0)
    _trace.record_span("pipeline/train", t0, trace_id=trace_id,
                       span_id=span_id,
                       attrs={"generation": gen, "rc": rc})
    # the supervisor's own spans land in pipeline.jsonl directly —
    # there is no recorder on this side to drain them
    for ev in _trace.drain_span_events():
        events.write(ev)
    events.write({"event": "pipeline", "phase": "train_done",
                  "generation": gen, "rc": rc, "time": time.time()})
    return rc


def _start_fleet(args, dirs: Dict[str, str], base_port: int,
                 serve_faults: str) -> subprocess.Popen:
    env = dict(os.environ)
    if serve_faults:
        env["LIGHTGBM_TPU_FAULT_INJECT"] = serve_faults
    else:
        env.pop("LIGHTGBM_TPU_FAULT_INJECT", None)
    env["LIGHTGBM_TPU_TELEMETRY"] = os.path.join(
        dirs["telemetry"], "serve.jsonl")
    cmd = [sys.executable, "-m", "lightgbm_tpu", "launch",
           str(args.replicas),
           "--max-restarts", str(args.max_restarts),
           "--max-restarts-per-window",
           str(args.max_restarts_per_window),
           "--restart-window", str(args.restart_window),
           "--health-port", str(base_port),
           "--health-interval", str(args.health_interval),
           "--health-grace", str(args.health_grace),
           "--grace", str(args.grace),
           # rollback guard: the fleet supervisor watches the publish
           # target, adopts publications the fleet serves and rolls a
           # canary-refused one back to last-known-good
           "--publish-dir", dirs["publish"],
           "--rollback-grace", str(args.rollback_grace),
           # fleet scrape cadence: per-replica QPS/p99/shed/restarts
           # into telemetry/serve.jsonl.fleet (docs/OBSERVABILITY.md)
           "--scrape-interval", str(args.scrape_interval),
           "--log-dir", os.path.join(dirs["logs"], "fleet"), "--",
           sys.executable, "-m", "lightgbm_tpu", "serve",
           dirs["publish"],
           "--port", str(base_port),
           "--watch-dir", dirs["publish"],
           "--watch-interval", "0.25",
           "--stats-interval", "1.0",
           "--shed-queue-rows", str(args.shed_queue_rows),
           "--shed-p99-ms", str(args.shed_p99_ms),
           "--grace", str(args.grace)]
    if args.max_replicas > 0:
        # replica autoscaling (resilience/autoscale.py): the fleet
        # supervisor spawns/retires replicas from the scrape signal
        idx = cmd.index("--log-dir")
        cmd[idx:idx] = [
            "--min-replicas", str(args.min_replicas or args.replicas),
            "--max-replicas", str(args.max_replicas),
            "--autoscale-up-qps", str(args.autoscale_up_qps),
            "--autoscale-down-qps", str(args.autoscale_down_qps),
            "--autoscale-up-p99-ms", str(args.autoscale_up_p99_ms),
            "--retire-grace", str(args.retire_grace)]
    if args.metrics_port:
        # fleet supervisor /metrics at base+2; it exports base+3 so
        # serve replica r binds base+3+r (the daemon adds its rank)
        idx = cmd.index("--log-dir")
        cmd[idx:idx] = ["--metrics-port", str(args.metrics_port + 2)]
    log_path = os.path.join(dirs["logs"], "fleet_supervisor.log")
    log_file = open(log_path, "ab")
    try:
        proc = subprocess.Popen(cmd, env=env, stdout=log_file,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
    finally:
        log_file.close()
    return proc


def _wait_fleet_ready(ports: List[int], timeout: float) -> bool:
    from .resilience.elastic import replica_ping
    deadline = time.monotonic() + timeout
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in sorted(pending):
            if replica_ping(port, timeout=2.0):
                pending.discard(port)
        if pending:
            time.sleep(0.5)
    return not pending


def _confirm_swap(ports: List[int], want_sha: str,
                  timeout: float) -> bool:
    """Every replica reports a manifest-validated swap to the
    publication identified by ``want_sha`` (replicas may briefly
    disagree mid-rollout — or be mid-restart under chaos)."""
    deadline = time.monotonic() + timeout
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in sorted(pending):
            st = replica_stats(port, timeout=5.0)
            manifest = (st or {}).get("manifest") or {}
            if manifest.get("sha256") == want_sha:
                pending.discard(port)
        if pending:
            time.sleep(0.5)
    return not pending


def _read_fleet_events(path: str) -> List[Dict[str, Any]]:
    """Every parseable JSONL event in the fleet supervisor's stream
    (``telemetry/serve.jsonl.fleet``); [] when absent — the reader
    side of the autoscale / rollback confirmation."""
    events: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    events.append(ev)
    except OSError:
        pass
    return events


def _await_rollback(fleet_stream: str, bad_sha: str,
                    timeout: float) -> Optional[Dict[str, Any]]:
    """The fleet supervisor's ``{"event": "rollback"}`` record for
    ``bad_sha``, polling up to ``timeout`` seconds; None when the
    fleet never rolled that publication back."""
    deadline = time.monotonic() + timeout
    while True:
        for ev in _read_fleet_events(fleet_stream):
            if ev.get("event") == "rollback" \
                    and ev.get("bad_sha") == bad_sha:
                return ev
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.5)


def _fleet_lifecycle_summary(fleet_stream: str) -> Dict[str, Any]:
    """Autoscale / rollback / replica-peak roll-up from the fleet
    stream — the summary's proof that scaling and rollback actually
    happened (or didn't)."""
    ups = downs = rollbacks = 0
    peak = 0
    for ev in _read_fleet_events(fleet_stream):
        kind = ev.get("event")
        if kind == "autoscale":
            if ev.get("action") == "up":
                ups += 1
            elif ev.get("action") == "down":
                downs += 1
            peak = max(peak, int(ev.get("replicas") or 0))
        elif kind == "rollback":
            rollbacks += 1
        elif kind == "fleet":
            alive = sum(1 for r in (ev.get("replicas") or [])
                        if r.get("alive"))
            peak = max(peak, alive)
    return {"scale_ups": ups, "scale_downs": downs,
            "rollbacks": rollbacks, "replicas_peak": peak}


def _shutdown_fleet(fleet: subprocess.Popen, ports: List[int],
                    grace: float) -> None:
    """Graceful: ask every replica to drain and exit 0, so the fleet
    supervisor sees a clean fleet and exits 0 itself."""
    for port in ports:
        _rpc(port, {"cmd": "shutdown"}, timeout=5.0)
    try:
        fleet.wait(timeout=max(30.0, 2 * grace))
        return
    except subprocess.TimeoutExpired:
        pass
    from .resilience.elastic import _kill_group
    _kill_group(fleet)


def main(argv: Optional[List[str]] = None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    if args.train_worker is not None:
        # jax side: one supervised retrain generation
        return _train_worker(args)

    workdir = os.path.abspath(args.workdir)
    dirs = {name: os.path.join(workdir, name)
            for name in ("publish", "checkpoints", "telemetry",
                         "logs")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)
    if args.generations < 1 or args.replicas < 1:
        print("pipeline: --generations and --replicas must be >= 1",
              file=sys.stderr)
        return 2
    fault_spec = args.fault_inject \
        if args.fault_inject is not None \
        else os.environ.get("LIGHTGBM_TPU_FAULT_INJECT", "")
    train_faults, serve_faults = _split_faults(fault_spec)

    from .resilience.elastic import _free_port
    from .resilience.publisher import latest_manifest
    base_port = args.port or _free_port()
    if args.max_replicas > 0:
        # autoscaling fleet: swap confirmation polls only the
        # always-active floor (the autoscaler retires from the top
        # rank down, so ranks below the floor never disappear); the
        # load generator targets the whole potential range and backs
        # off ports that are legitimately down
        floor = min(args.replicas, args.min_replicas or args.replicas)
        span = max(args.replicas, args.max_replicas)
    else:
        floor = span = args.replicas
    ports = [base_port + r for r in range(max(1, floor))]
    ready_ports = [base_port + r for r in range(args.replicas)]
    all_ports = [base_port + r for r in range(span)]
    fleet_stream = os.path.join(dirs["telemetry"],
                                "serve.jsonl.fleet")
    events = _EventLog(os.path.join(dirs["telemetry"],
                                    "pipeline.jsonl"))
    client_metrics = _ClientMetrics()
    if args.metrics_port:
        # the supervisor's own jax-free /metrics: supervisor counters
        # (restart budget, publish totals) + the loadgen client view
        from .obs.export import ensure_metrics_server
        ensure_metrics_server(args.metrics_port,
                              extra_families=client_metrics.families)
    events.write({"event": "pipeline", "phase": "start",
                  "generations": args.generations,
                  "replicas": args.replicas, "ports": all_ports,
                  "max_replicas": args.max_replicas,
                  "warm_start": args.warm_start,
                  "fault_inject": fault_spec, "time": time.time()})
    fleet: Optional[subprocess.Popen] = None
    loadgen: Optional[LoadGenerator] = None
    spike_stop = threading.Event()
    failures: List[str] = []
    rollbacks: List[Dict[str, Any]] = []
    swaps_confirmed = 0
    published: List[Dict[str, Any]] = []
    try:
        # ---- generation 0: bootstrap model, then bring up the fleet
        rc = _train_generation(args, 0, dirs, train_faults, events)
        if rc != 0:
            failures.append(f"generation 0 training failed (exit {rc})")
            return _finish(args, events, failures, published,
                           swaps_confirmed, None, loadgen,
                           rollbacks, fleet_stream)
        first = latest_manifest(dirs["publish"])
        if first is None:
            failures.append("generation 0 published nothing usable")
            return _finish(args, events, failures, published,
                           swaps_confirmed, None, loadgen,
                           rollbacks, fleet_stream)
        published.append(first[1])
        fleet = _start_fleet(args, dirs, base_port, serve_faults)
        if not _wait_fleet_ready(ready_ports,
                                 timeout=args.swap_timeout):
            failures.append(f"serve fleet never became ready on "
                            f"ports {ready_ports}")
            return _finish(args, events, failures, published,
                           swaps_confirmed, None, loadgen,
                           rollbacks, fleet_stream)
        events.write({"event": "pipeline", "phase": "fleet_ready",
                      "ports": ready_ports, "time": time.time()})
        if args.request_rate > 0:
            loadgen = LoadGenerator(
                all_ports, args.features,
                rate_per_sec=args.request_rate,
                rows_per_request=args.request_rows,
                event_log=events, trace_every=args.trace_every)
            loadgen.start()
            client_metrics.attach(loadgen)
            if args.spike_rate > 0:
                threading.Thread(
                    target=_drive_spike,
                    args=(loadgen, events, args.request_rate,
                          args.spike_rate, args.spike_start,
                          args.spike_duration, spike_stop),
                    daemon=True,
                    name="lightgbm-tpu-pipeline-spike").start()
        # the bootstrap model was loaded at startup, not hot-swapped:
        # confirm the fleet serves it before retraining begins
        if not _confirm_swap(ports, first[1]["sha256"],
                             timeout=args.swap_timeout):
            # startup path reports no manifest (the daemon loaded the
            # file directly): fall back to source-path confirmation
            ok = all((replica_stats(p, timeout=5.0) or {})
                     .get("model_source") == first[0] for p in ports)
            if not ok:
                failures.append(
                    "fleet did not confirm the bootstrap model")

        # ---- retrain generations
        for gen in range(1, args.generations):
            rc = _train_generation(args, gen, dirs, train_faults,
                                   events)
            if rc != 0:
                failures.append(
                    f"generation {gen} training failed (exit {rc})")
                break
            latest = latest_manifest(dirs["publish"])
            if latest is None or latest[1].get("generation") != gen:
                failures.append(
                    f"generation {gen} publication missing/invalid")
                break
            published.append(latest[1])
            if _confirm_swap(ports, latest[1]["sha256"],
                             timeout=args.swap_timeout):
                swaps_confirmed += 1
                events.write({"event": "pipeline",
                              "phase": "swap_confirmed",
                              "generation": gen,
                              "sha256": latest[1]["sha256"],
                              "time": time.time()})
                continue
            # the fleet refused the swap: a canary-gated rollback by
            # the fleet supervisor is SUCCESS for the service (the
            # fleet kept last-known-good and superseded the bad
            # publication), not a pipeline failure
            rb = _await_rollback(fleet_stream, latest[1]["sha256"],
                                 timeout=min(60.0, args.swap_timeout))
            if rb is not None:
                rollbacks.append(
                    {"generation": gen,
                     "bad_sha": rb.get("bad_sha"),
                     "good_sha": rb.get("good_sha"),
                     "good_file": rb.get("good_file")})
                events.write({"event": "pipeline",
                              "phase": "rollback_confirmed",
                              "generation": gen, **{
                                  k: rb.get(k)
                                  for k in ("bad_sha", "good_sha",
                                            "good_file")},
                              "time": time.time()})
                good_sha = rb.get("good_sha")
                if good_sha and not _confirm_swap(
                        ports, good_sha, timeout=args.swap_timeout):
                    failures.append(
                        f"fleet rolled generation {gen} back but "
                        "never converged on the last-known-good "
                        f"model {good_sha[:12]}")
                    break
                continue
            failures.append(
                f"fleet never confirmed generation {gen}'s "
                "publication within the swap timeout")
            break
        return _finish(args, events, failures, published,
                       swaps_confirmed, ports, loadgen,
                       rollbacks, fleet_stream)
    finally:
        spike_stop.set()
        if loadgen is not None:
            loadgen.stop()
        if fleet is not None and not args.keep_fleet:
            _shutdown_fleet(fleet, all_ports, args.grace)
        elif fleet is not None:
            log_info(f"pipeline: fleet left running on ports "
                     f"{all_ports} (--keep-fleet)")
        events.close()


def _finish(args, events: _EventLog, failures: List[str],
            published: List[Dict[str, Any]], swaps_confirmed: int,
            ports: Optional[List[int]],
            loadgen: Optional[LoadGenerator],
            rollbacks: Optional[List[Dict[str, Any]]] = None,
            fleet_stream: Optional[str] = None) -> int:
    client = None if loadgen is None else loadgen.snapshot()
    summary: Dict[str, Any] = {
        "event": "pipeline_summary",
        "generations_requested": args.generations,
        "generations_published": len(published),
        "swaps_confirmed": swaps_confirmed,
        "rollbacks": rollbacks or [],
        "last_published_sha256":
            published[-1]["sha256"] if published else None,
        "last_published_generation":
            published[-1].get("generation") if published else None,
        "train_auc_by_generation":
            [m.get("train_auc") for m in published],
        "failures": failures,
        "time": time.time(),
    }
    if ports:
        fleet_stats = [replica_stats(p, timeout=5.0) for p in ports]
        summary["fleet"] = [
            None if st is None else
            {"model": st.get("model"),
             "model_source": st.get("model_source"),
             "manifest_sha256":
                 (st.get("manifest") or {}).get("sha256"),
             "requests_total": st.get("requests_total"),
             "shed_total": st.get("shed_total"),
             "swap_failures": st.get("swap_failures"),
             "swaps_total": st.get("swaps_total")}
            for st in fleet_stats]
    if fleet_stream is not None:
        summary["fleet_lifecycle"] = \
            _fleet_lifecycle_summary(fleet_stream)
    summary["client"] = client
    events.write(summary)
    print(json.dumps(summary), flush=True)
    if failures:
        for f in failures:
            log_warning(f"pipeline: FAILED: {f}")
        return 1
    log_info(f"pipeline: {len(published)} generation(s) trained, "
             f"published and served; last model "
             f"{summary['last_published_sha256'][:12]}…")
    return 0
