# tpulint fixture: TPL003 negative — stable trace signatures.
import functools

import jax
import jax.numpy as jnp


def _impl(x, n):
    return x * n


stepper = jax.jit(_impl, static_argnums=(1,))

# module level, outside any loop: compiled once
hoisted = jax.jit(lambda v: v * 2)


def ok(xs, cfg):
    out = []
    for _ in range(3):
        # statics derived from shapes/config are stable per dataset
        out.append(stepper(xs, xs.shape[0]))
        out.append(stepper(xs, cfg.num_leaves))
        out.append(hoisted(xs))
    # literal statics never retrace
    return out + [stepper(xs, 4)]
