"""``python -m lightgbm_tpu serve <model>``: the inference daemon.

A stdlib-socket JSON-lines server over one compiled forest
(serve/compile.py) and one micro-batcher (serve/batcher.py):

- **Protocol** (one JSON object per line, one JSON reply per line)::

      {"rows": [[...], ...]}            -> {"predictions": [...], ...}
      {"rows": [...], "raw": true}      -> raw scores, no objective
                                           transform
      {"cmd": "ping"}                   -> {"ok": true, "model": ...,
                                            "pid": ...}
      {"cmd": "stats"}                  -> queue/latency/model snapshot
      {"cmd": "metrics"}                -> OpenMetrics text (the
                                           /metrics render over the
                                           protocol; obs/export.py)
      {"cmd": "shutdown"}               -> stops the daemon (testing /
                                           drains first)

- **Hot model swap**: ``--watch-dir`` polls a watch target — a local
  directory, or any artifact-store spec (``mem://<name>``, an
  :class:`~..resilience.store.ArtifactStore`; resilience/store.py) —
  for the newest model artifact: ``ckpt_*.npz`` training snapshots
  (resilience/checkpoint.py, local targets only) or ``*.txt`` model
  files, both written via the store's all-or-nothing put (the
  same-dir-tmp + ``os.replace`` convention on a local directory,
  utils/atomic.py) — compiles it off the serving path, and swaps it
  into the batcher. In-flight requests finish on the model they
  started with; the old forest's HBM is donated to the new upload.
  Artifacts published with a manifest sidecar
  (resilience/publisher.py, docs/PIPELINE.md) are sha256-validated
  first: a TORN publication is skipped with a ``swap_failure`` fault
  event and retried next poll, never served. A manifest that embeds a
  **canary** (validation rows + the publisher's expected raw scores)
  gates the swap harder: the staged forest scores the canary through
  the real compiled path BEFORE the swap is offered, and a mismatch
  refuses the swap with a ``canary_refused`` fault event — a
  byte-valid-but-wrong publication (``publish_poison``) never serves.
  A store outage mid-poll degrades to serving the current model (with
  a warning + fault event), never a crash.

- **Overload policy**: beyond the hard ``QueueFullError`` admission
  wall, ``--shed-queue-rows`` / ``--shed-p99-ms`` shed the OLDEST
  queued requests with a typed ``{"shed": true}`` reply
  (docs/SERVING.md "Overload policy").

- **Graceful shutdown**: SIGTERM and the ``shutdown`` command drain
  accepted requests (bounded by ``--grace``) before the socket
  closes — a supervised restart never drops an accepted request.
  During the drain the daemon keeps ACCEPTING briefly and answers new
  predict requests with a typed ``{"error": "draining"}`` reply — a
  connection parked in the TCP accept backlog at SIGTERM gets a fast
  typed refusal to retry elsewhere, never a hang against a
  closed-but-unaccepted socket (docs/SERVING.md "Shutdown").

- **Telemetry**: ``{"event": "serve"}`` JSONL lines every
  ``--stats-interval`` seconds (QPS, queue depth, p50/p99 latency,
  recompile counter, HBM gauges, swap count) to ``--telemetry`` or
  ``$LIGHTGBM_TPU_TELEMETRY``; ``python -m lightgbm_tpu stats`` folds
  them into a serve summary row.

- **Multi-replica**: under ``python -m lightgbm_tpu launch N -- python
  -m lightgbm_tpu serve ...`` each rank serves on ``--port + rank``
  and the supervisor restarts the world when a replica dies
  (docs/SERVING.md).

This module's import surface and its CLI parse path (``--help``,
missing-model errors) are jax-free — the dispatch in ``__main__`` runs
before the training CLI loads, and jax is only imported once a model
is actually loaded and compiled (proved by a subprocess test, like
``lint``).
"""

from __future__ import annotations

import argparse
import json
import os
import socketserver
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.log import log_info, log_warning

__all__ = ["main", "build_parser", "handle_request", "ServeState"]


# ---------------------------------------------------------------------
# serving state (model + batcher + telemetry), shared across the
# request-handler, watcher and stats threads
# ---------------------------------------------------------------------

class ServeState:
    """Everything the handler/watcher/stats threads share.

    Threading contract (tpulint TPL006/TPL008 over serve/): mutable
    fields are only touched under ``self._lock``; model compilation
    and jax dispatch always happen outside it.
    """

    def __init__(self, batcher, model_id: str, model_source: str,
                 registry=None, telemetry_path: Optional[str] = None,
                 manifest: Optional[Dict[str, Any]] = None):
        from ..obs import RecompileWatcher
        from ..obs.registry import registry as global_registry
        from ..resilience.faults import FaultPlan
        self.batcher = batcher
        self.registry = registry if registry is not None \
            else global_registry
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._model_id = model_id
        self._model_source = model_source
        self._manifest: Optional[Dict[str, Any]] = \
            dict(manifest) if manifest else None
        self._swap_failures = 0
        self._shed_replies = 0
        self._requests_accepted = 0
        self._active_handlers = 0
        self._draining = False
        self._last_stats: Dict[str, Any] = {}
        # newest computed rates (qps / rows_per_sec), cached so the
        # /metrics scrape can export them WITHOUT consuming the
        # stats() rate window (scrapes must never shrink the serve
        # event cadence's window)
        self._last_rates: Dict[str, Any] = {}
        self._telemetry_file = None
        self.shutdown_event = threading.Event()
        self._t0 = time.monotonic()
        self._watcher = RecompileWatcher()
        self.fault_plan = FaultPlan.from_env()
        if telemetry_path:
            try:
                dirname = os.path.dirname(os.path.abspath(
                    telemetry_path))
                os.makedirs(dirname, exist_ok=True)
                self._telemetry_file = open(telemetry_path, "a",
                                            encoding="utf-8")
            except OSError as e:
                log_warning(f"serve: cannot open telemetry path "
                            f"{telemetry_path!r} ({e}); serve events "
                            "will not be written")

    # -- model identity ------------------------------------------------
    def model_id(self) -> str:
        with self._lock:
            return self._model_id

    def model_source(self) -> str:
        with self._lock:
            return self._model_source

    def note_swap(self, model_id: str, source: str,
                  manifest: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            self._model_id = model_id
            self._model_source = source
            self._manifest = dict(manifest) if manifest else None
        self.registry.counter("serve_swaps").inc()

    def note_swap_failure(self) -> None:
        with self._lock:
            self._swap_failures += 1
        self.registry.counter("serve_swap_failures").inc()

    def note_shed(self) -> None:
        with self._lock:
            self._shed_replies += 1
        self.registry.counter("serve_shed_requests").inc()

    def count_request(self) -> int:
        """Ordinal of this accepted predict request (1-based), feeding
        the ``serve_kill@N`` chaos hook."""
        with self._lock:
            self._requests_accepted += 1
            return self._requests_accepted

    # -- graceful shutdown bookkeeping ---------------------------------
    # in-flight REQUEST accounting, not connection accounting: a
    # handler blocked reading an idle keep-alive connection has no
    # reply pending and must not make the drain wait out the whole
    # grace deadline
    def handler_enter(self) -> None:
        with self._lock:
            self._active_handlers += 1

    def handler_exit(self) -> None:
        with self._lock:
            self._active_handlers -= 1

    def active_handlers(self) -> int:
        with self._lock:
            return self._active_handlers

    def request_shutdown(self) -> None:
        self.shutdown_event.set()

    def begin_drain(self) -> None:
        """Flip predict requests to the typed ``{"error": "draining"}``
        refusal; ``ping``/``stats``/``metrics`` keep answering so the
        supervisor can observe the retirement."""
        with self._lock:
            self._draining = True

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # -- telemetry -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The ``stats`` protocol reply / serve-event payload.

        Rates cover the window since the PREVIOUS stats() call by any
        consumer. The rate baseline, the recompile watcher (whose
        ``delta()`` mutates its own fields), and the model metadata
        are all read-modify-written inside ONE locked section —
        concurrent pollers (the stats loop + protocol clients) must
        not double-count a window or tear the watcher. The device
        queries stay outside the lock (TPL006)."""
        from ..obs import device_memory_stats
        snap = self.batcher.stats()
        hbm = device_memory_stats()         # jax query outside the lock
        with self._lock:
            model_id = self._model_id
            source = self._model_source
            manifest = dict(self._manifest) if self._manifest else None
            failures = self._swap_failures
            shed_replies = self._shed_replies
            draining = self._draining
            last = dict(self._last_stats)
            uptime = time.monotonic() - self._t0
            recompiles = {"delta": self._watcher.delta(),
                          "total": self._watcher.total}
            self._last_stats = {"uptime_s": uptime,
                                "requests_total": snap["requests_total"],
                                "rows_total": snap["rows_total"]}
        dt = uptime - last.get("uptime_s", 0.0)
        dreq = snap["requests_total"] - last.get("requests_total", 0)
        drows = snap["rows_total"] - last.get("rows_total", 0)
        out = dict(snap)
        out["model"] = model_id
        out["model_source"] = source
        out["manifest"] = manifest
        out["swap_failures"] = failures
        out["shed_replies"] = shed_replies
        out["draining"] = draining
        out["uptime_s"] = round(uptime, 3)
        out["qps"] = round(dreq / dt, 3) if dt > 0 else 0.0
        out["rows_per_sec"] = round(drows / dt, 3) if dt > 0 else 0.0
        out["recompiles"] = recompiles
        out["hbm"] = hbm
        gauge = self.registry.gauge("serve_queue_depth_rows")
        gauge.set(snap["queue_depth_rows"])
        with self._lock:
            self._last_rates = {"qps": out["qps"],
                                "rows_per_sec": out["rows_per_sec"]}
        return out

    # -- OpenMetrics export (obs/export.py) ----------------------------
    def metrics_families(self) -> Dict[str, Any]:
        """Serve-side families merged into the /metrics render and the
        ``{"cmd": "metrics"}`` protocol verb: the batcher's cumulative
        counters and latency percentiles (non-destructive reads), the
        newest rate window computed by the stats cadence, HBM gauges,
        and the serving model identity as an info-style labeled gauge.
        Runs on scrape/handler threads: shared fields are read under
        ``self._lock``, device queries outside it (TPL006/TPL008)."""
        from ..obs import device_memory_stats
        from ..obs.export import counter_family, gauge_family
        snap = self.batcher.stats()
        hbm = device_memory_stats()
        with self._lock:
            model_id = self._model_id
            rates = dict(self._last_rates)
            # the serving model's publication sha rides the info gauge
            # so the fleet supervisor's rollback guard can see WHICH
            # publication each replica runs (resilience/autoscale.py)
            sha = (self._manifest or {}).get("sha256") or ""
        fams: Dict[str, Any] = {
            "serve_requests": counter_family(snap["requests_total"]),
            "serve_rows": counter_family(snap["rows_total"]),
            "serve_batches": counter_family(snap["batches_total"]),
            "serve_rejected": counter_family(snap["rejected_total"]),
            "serve_shed": counter_family(snap["shed_total"]),
            "serve_shed_rows": counter_family(snap["shed_rows"]),
            "serve_queue_depth_rows":
                gauge_family(snap["queue_depth_rows"]),
            "serve_p50_ms": gauge_family(snap["p50_ms"]),
            "serve_p99_ms": gauge_family(snap["p99_ms"]),
            "serve_qps": gauge_family(rates.get("qps")),
            "serve_rows_per_sec":
                gauge_family(rates.get("rows_per_sec")),
            "serve_model_info": gauge_family(1, model=str(model_id),
                                             sha=str(sha)),
        }
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            if hbm.get(key) is not None:
                fams[f"hbm_{key}"] = gauge_family(hbm[key])
        return fams

    def render_metrics(self) -> str:
        """OpenMetrics text for the protocol verb: the process
        registry (swaps/sheds/xla compiles) plus the serve families.
        Snapshot under the registry lock, render outside (TPL006)."""
        from ..obs.export import render_openmetrics
        return render_openmetrics(self.registry.snapshot(),
                                  extra=self.metrics_families())

    def emit_serve_event(self) -> None:
        """One ``{"event": "serve"}`` JSONL line (degrades like the
        training recorder: an unwritable file stops the stream, never
        serving). Process-level fault events (``swap_failure`` from
        the watcher, shed records) are drained into the stream first,
        mirroring the training recorder's contract that fault lines
        precede the event that observed them."""
        faults: List[dict] = []
        try:
            from ..resilience.faults import FAULT_EVENTS, drain_events
            if FAULT_EVENTS:
                faults = drain_events(FAULT_EVENTS)
        except Exception:
            pass
        try:
            # bucket compiles carry their cost attribution into the
            # stream (obs/cost.py); drained like fault events
            from ..obs.cost import drain_compile_events
            faults = faults + drain_compile_events()
        except Exception:
            pass
        try:
            # per-request / swap spans (obs/trace.py) ride the serve
            # stream on the stats cadence, like faults and compiles
            from ..obs.trace import drain_span_events
            faults = faults + drain_span_events()
        except Exception:
            pass
        payload = {"event": "serve", **self.stats()}
        with self._lock:
            fh = self._telemetry_file
            if fh is None:
                return
            try:
                for ev in faults:
                    fh.write(json.dumps(ev) + "\n")
                fh.write(json.dumps(payload) + "\n")
                fh.flush()
            except OSError as e:
                log_warning(f"serve: telemetry write failed ({e}); "
                            "stopping the event stream")
                try:
                    fh.close()
                except OSError:
                    pass
                self._telemetry_file = None

    def close(self) -> None:
        self.request_shutdown()
        self.batcher.close()
        with self._lock:
            fh, self._telemetry_file = self._telemetry_file, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass


# ---------------------------------------------------------------------
# request handling (pure function over ServeState: unit-testable
# without sockets)
# ---------------------------------------------------------------------

def handle_request(obj: Any, state: ServeState) -> Dict[str, Any]:
    """One protocol request -> one reply object."""
    if not isinstance(obj, dict):
        return {"error": "request must be a JSON object"}
    if "cmd" in obj:
        cmd = obj["cmd"]
        if cmd == "ping":
            return {"ok": True, "model": state.model_id(),
                    "pid": os.getpid()}
        if cmd == "stats":
            return {"ok": True, **state.stats()}
        if cmd == "metrics":
            # OpenMetrics text over the JSON protocol: what the HTTP
            # /metrics endpoint serves, for consumers already holding
            # a protocol connection (the fleet supervisor's scraper)
            from ..obs.export import CONTENT_TYPE
            try:
                body = state.render_metrics()
            except Exception as e:
                return {"error": f"metrics render failed: {e}"}
            return {"ok": True, "content_type": CONTENT_TYPE,
                    "metrics": body}
        if cmd == "shutdown":
            state.request_shutdown()
            return {"ok": True, "shutting_down": True}
        return {"error": f"unknown cmd: {cmd!r}"}
    rows = obj.get("rows", obj.get("features"))
    if rows is None:
        return {"error": "expected 'rows' (list of feature rows), "
                         "'features' (one row) or 'cmd'"}
    if state.draining():
        # graceful shutdown in progress: a typed refusal, not a hang —
        # the client retries on another replica immediately instead of
        # waiting out a connection that is about to close
        return {"error": "draining", "draining": True,
                "model": state.model_id()}
    import numpy as np
    try:
        X = np.asarray(rows, np.float32)
    except (TypeError, ValueError) as e:
        return {"error": f"rows are not a numeric matrix: {e}"}
    if X.ndim == 1:
        X = X[None, :]
    if X.ndim != 2 or X.shape[0] == 0:
        return {"error": f"rows must be [n, n_features], got shape "
                         f"{X.shape}"}
    # chaos hook (resilience/faults.py serve_kill@N): fires BEFORE the
    # request enters the batcher — a SIGKILLed replica must never hold
    # an accepted-but-unanswered request; the dying connection is the
    # client's retry signal
    state.fault_plan.maybe_serve_kill(state.count_request())
    # optional distributed-tracing context (obs/trace.py): a sampled
    # client sends {"trace": {"trace_id", "span_id"}} and this request
    # emits queue-wait / batch-window / dispatch / reply spans into the
    # serve telemetry stream, parented to the client's span
    trace_ctx = obj.get("trace")
    if not isinstance(trace_ctx, dict) \
            or not trace_ctx.get("trace_id"):
        trace_ctx = None
    from .batcher import QueueFullError, SheddingError
    try:
        fut = state.batcher.submit(X, trace=trace_ctx)
    except QueueFullError as e:
        return {"error": str(e), "overloaded": True}
    except (ValueError, RuntimeError) as e:
        return {"error": str(e)}
    try:
        raw_scores = fut.result()
    except SheddingError as e:       # typed overload reply: the client
        state.note_shed()            # should retry later / elsewhere
        return {"error": str(e), "shed": True, "overloaded": True,
                "model": state.model_id()}
    except Exception as e:                       # batch-level failure
        return {"error": f"prediction failed: {e}"}
    # finalize with the forest that PRODUCED the scores (stamped on
    # the future by the batcher worker): a hot swap completing between
    # dispatch and here must not apply the new model's objective
    # transform / rf averaging / class count to the old model's raw
    # scores
    forest = getattr(fut, "serving_forest", None)
    if forest is None:
        forest = state.batcher._current_forest()
    out = forest.finalize(raw_scores,
                          raw_score=bool(obj.get("raw", False)))
    model_id = state.model_id()
    times = getattr(fut, "trace_times", None)
    if trace_ctx is not None and times is not None:
        _record_request_spans(trace_ctx, times, model_id,
                              int(X.shape[0]))
    return {"predictions": out.tolist(), "n": int(X.shape[0]),
            "model": model_id}


def _record_request_spans(trace_ctx: Dict[str, Any], times, model_id,
                          n_rows: int) -> None:
    """Spans for one sampled request: a ``serve/request`` parent over
    submit -> reply, with queue-wait / batch-window / device-dispatch /
    reply children from the batcher's perf_counter checkpoints. Only
    runs for requests that CARRIED a trace context — never on the
    default path — and never raises into the reply."""
    try:
        from ..obs import trace as _trace
        t_submit, t_dequeue, t_dispatch, t_done = times
        now = time.perf_counter()
        tid = trace_ctx.get("trace_id")
        parent = _trace.record_span(
            "serve/request", t_submit, now, trace_id=tid,
            parent_id=trace_ctx.get("span_id"),
            attrs={"model": model_id, "rows": n_rows})
        for name, a, b in (
                ("serve/queue_wait", t_submit, t_dequeue),
                ("serve/batch_window", t_dequeue, t_dispatch),
                ("serve/dispatch", t_dispatch, t_done),
                ("serve/reply", t_done, now)):
            _trace.record_span(name, a, b, trace_id=tid,
                               parent_id=parent)
    except Exception:
        pass


# ---------------------------------------------------------------------
# socket server
# ---------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        state: ServeState = self.server.state  # type: ignore[attr-defined]
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            # count the REQUEST as in flight from parse to flushed
            # reply — the graceful drain waits for exactly this window,
            # never for handlers idling between pipelined requests
            state.handler_enter()
            try:
                try:
                    obj = json.loads(line.decode("utf-8"))
                except (UnicodeDecodeError, ValueError):
                    resp = {"error": "malformed JSON line"}
                else:
                    resp = handle_request(obj, state)
                try:
                    self.wfile.write((json.dumps(resp) + "\n")
                                     .encode("utf-8"))
                    self.wfile.flush()
                except OSError:
                    return                  # client went away mid-reply
            finally:
                state.handler_exit()
            if resp.get("shutting_down"):
                return
            if state.shutdown_event.is_set():
                # graceful drain: the reply for every request read
                # so far is on the wire; stop reading new ones and
                # close, so the client sees EOF, not a hang
                return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True       # supervised restarts rebind fast
    daemon_threads = True


# ---------------------------------------------------------------------
# model loading + watching
# ---------------------------------------------------------------------

def _is_model_name(name: str, local: bool) -> bool:
    """Artifact names the watcher considers: model text everywhere,
    checkpoint snapshots only on local targets (load_snapshot needs a
    real file; a cross-machine store publishes model text)."""
    if name.endswith(".txt"):
        return True
    return local and name.startswith("ckpt_") and name.endswith(".npz")


def _member_id(store, name: str) -> str:
    """Stable identity of one store member — the joined PATH on a
    local directory (the PR-12 watch keys, byte-for-byte), the
    ``url/name`` spec elsewhere."""
    from ..resilience.store import LocalDirStore
    if isinstance(store, LocalDirStore):
        return os.path.join(store.directory, name)
    return f"{store.url}/{name}"


def _find_model_artifact_in(store) -> Optional[Tuple[float, str]]:
    """Newest model artifact NAME in ``store``: (mtime, name).

    Raises ``OSError`` (``StoreError``) when the store itself cannot
    be listed — the watcher turns that into degraded-but-serving."""
    from ..resilience.store import LocalDirStore
    local = isinstance(store, LocalDirStore)
    best: Optional[Tuple[float, str]] = None
    for name in store.list_names():
        if not _is_model_name(name, local):
            continue
        st = store.stat(name)
        if st is None:
            continue
        key = (st[0], name)
        if best is None or key > best:
            best = key
    return best


def _find_model_artifact(directory: str) \
        -> Optional[Tuple[float, str]]:
    """Newest model artifact in directory ``directory``:
    (mtime, path)."""
    from ..resilience.store import LocalDirStore
    try:
        found = _find_model_artifact_in(LocalDirStore(directory))
    except OSError:
        return None
    if found is None:
        return None
    mtime, name = found
    return (mtime, os.path.join(directory, name))


def _load_booster(path: str):
    """A Booster from either a model text file or a training
    checkpoint snapshot (the daemon serves straight from the
    checkpoint directory the trainer writes into). A file that parses
    to ZERO trees is rejected — the lenient model-text parser would
    otherwise let any stray .txt in a watch dir replace a good model
    with one that predicts constants."""
    from ..basic import Booster, LightGBMError
    if path.endswith(".npz"):
        from ..resilience.checkpoint import load_snapshot
        snap = load_snapshot(path)
        booster = Booster(model_str=snap["model_str"])
    else:
        booster = Booster(model_file=path)
    if not booster._models:
        raise LightGBMError(f"{path}: parsed to a model with no trees")
    return booster


def _load_booster_in(store, name: str):
    """A Booster from one store member; local targets keep the
    path-based loader (checkpoint snapshots need a real file)."""
    from ..resilience.store import LocalDirStore
    if isinstance(store, LocalDirStore):
        return _load_booster(os.path.join(store.directory, name))
    from ..basic import Booster, LightGBMError
    booster = Booster(
        model_str=store.get_bytes(name).decode("utf-8"))
    if not booster._models:
        raise LightGBMError(f"{_member_id(store, name)}: parsed to a "
                            "model with no trees")
    return booster


def _artifact_key(path: str) -> Tuple[str, float, int]:
    st = os.stat(path)
    return (path, st.st_mtime, st.st_size)


def _artifact_key_in(store, name: str) -> Tuple[str, float, int]:
    """(identity, mtime, size) — the same key :func:`_artifact_key`
    produces for a local-directory member, so watch state primed from
    a path keeps matching once the watcher polls through a store."""
    st = store.stat(name)
    if st is None:
        raise FileNotFoundError(_member_id(store, name))
    return (_member_id(store, name), st[0], st[1])


class _Watcher:
    """Polls a watch target (directory / store spec / ArtifactStore)
    and hot-swaps the newest model artifact into the batcher. Runs on
    its own thread; compilation happens here, off the serving path,
    and the swap itself is one locked pointer exchange inside the
    batcher."""

    def __init__(self, state: ServeState, watch_dir,
                 interval_s: float, compile_kwargs: Dict[str, Any],
                 current_key: Optional[Tuple[str, float, int]],
                 warmup_rows: Optional[int]):
        from ..resilience.store import store_for
        self.state = state
        self.store = store_for(watch_dir)
        self.watch_dir = self.store.url
        self.interval_s = max(0.05, float(interval_s))
        self.compile_kwargs = dict(compile_kwargs)
        self.warmup_rows = warmup_rows
        self._last_key = current_key
        self._failed_key: Optional[Tuple[str, float, int]] = None
        self._degraded = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="lightgbm-tpu-serve-watcher")

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        while not self.state.shutdown_event.wait(self.interval_s):
            self.poll_once()

    def poll_once(self) -> bool:
        """One poll; True when a swap happened (tests call this
        directly for determinism)."""
        try:
            found = _find_model_artifact_in(self.store)
        except OSError as e:
            # a store outage must DEGRADE, not crash the watcher
            # thread: keep serving the current model, say so once per
            # outage episode, retry next poll
            if not self._degraded:
                self._degraded = True
                log_warning(f"serve: watch target {self.watch_dir!r} "
                            f"unreachable ({e}); serving the current "
                            "model and retrying next poll")
                from ..resilience.faults import record_fault_event
                record_fault_event(
                    "store_outage", action="degraded",
                    detail=f"watch target {self.watch_dir} "
                           f"unreachable: {e}")
            return False
        self._degraded = False
        if found is None:
            return False
        _, name = found
        try:
            key = _artifact_key_in(self.store, name)
        except OSError:
            return False
        path = _member_id(self.store, name)
        # self._last_key/_failed_key are only touched on this thread
        # (and the constructor, which runs before it starts)
        if key == self._last_key:
            return False
        try:
            # manifest validation first (resilience/publisher.py): a
            # managed artifact whose bytes mismatch its manifest is a
            # TORN publication — a publisher died between its manifest
            # and model writes, or a non-atomic writer is mid-way —
            # and must be skipped, not served. Unmanaged artifacts
            # (no sidecar) keep the legacy trust-once-it-parses path.
            from ..resilience.publisher import validate_artifact_in
            t_poll = time.perf_counter()
            manifest = validate_artifact_in(self.store, name)
            t_valid = time.perf_counter()
            booster = _load_booster_in(self.store, name)
            t_load = time.perf_counter()
            from .compile import compile_forest
            old = self.state.batcher._current_forest()
            # stage HOST-side on this thread (no HBM, no serving
            # pause); the worker-side attach below does the upload
            staged = compile_forest(booster, stage=True,
                                    **self.compile_kwargs)
            if staged.n_features != old.n_features:
                raise ValueError(
                    f"new model expects {staged.n_features} features, "
                    f"the served one {old.n_features} — clients would "
                    "break; refusing the swap")
            canary_forest = self._score_canary(manifest, staged, key)
            # the swap rides the request queue: the worker applies it
            # between batches, where the old forest is provably idle.
            # On the canary path the new forest is ALREADY attached
            # (it had to predict for real); otherwise attach() DONATES
            # the old forest's device buffers field-by-field to the
            # new upload — the transient HBM overhead is one field,
            # never a second resident forest
            t_stage = time.perf_counter()
            if canary_forest is not None:
                fut = self.state.batcher.swap_deferred(
                    lambda old_forest: canary_forest)
            else:
                fut = self.state.batcher.swap_deferred(
                    lambda old_forest: staged.attach(reuse=old_forest))
            try:
                forest = fut.result(timeout=300)
            except Exception:
                # a swap whose outcome we stop observing must never
                # apply later with the served identity unreported —
                # cancel it; if it raced in anyway, take its result
                if not fut.cancel() and fut.done() \
                        and fut.exception() is None:
                    forest = fut.result()
                else:
                    raise
        except Exception as e:
            # a torn/half-trained/corrupt artifact must never take
            # down the old model, OR poison the watcher: _last_key is
            # left unadvanced so the NEXT poll retries — a mid-write
            # file's atomic replacement lands momentarily. The fault
            # event and the warning fire once per observed key (the
            # counter still counts every failed attempt).
            first_sighting = key != self._failed_key
            self._failed_key = key
            if first_sighting:
                log_warning(f"serve: hot swap from {path!r} failed "
                            f"({e}); keeping the current model and "
                            "retrying next poll")
                from ..resilience.faults import record_fault_event
                record_fault_event(
                    "swap_failure", action="retry_next_poll",
                    detail=f"hot swap from {path} failed: {e}")
            self.state.note_swap_failure()
            return False
        self._last_key = key
        self._failed_key = None
        # identity updates the moment the new model SERVES; warmup is
        # an optimization and its failure is not a failed swap (the
        # buckets just compile lazily on traffic)
        self.state.note_swap(forest.model_id, path, manifest=manifest)
        self._record_swap_spans(
            manifest, path, forest.model_id,
            (t_poll, t_valid, t_load, t_stage, time.perf_counter()))
        log_info(f"serve: hot-swapped model from {path} "
                 f"(id {forest.model_id})")
        if self.warmup_rows != 0:
            try:
                forest.warmup(self.warmup_rows)
            except Exception as e:
                log_warning(f"serve: post-swap warmup failed ({e}); "
                            "buckets will compile on demand")
        return True

    def _score_canary(self, manifest, staged, key):
        """Canary gate (docs/SERVING.md): score the manifest's
        embedded validation rows through the REAL compiled forest
        before the swap is offered. Returns the attached forest on a
        pass (it is the one the swap installs — what was validated is
        what serves), None when the publication carries no canary or
        the serve-side ``--num-iteration`` trim makes the publisher's
        full-model expectations inapplicable; raises on a mismatch
        (the ``publish_poison`` shape), which the caller's failure
        path turns into an unswapped retry."""
        canary = (manifest or {}).get("canary")
        if not canary:
            return None
        trim = self.compile_kwargs.get("num_iteration")
        if trim is not None and int(trim) > 0:
            log_info("serve: skipping canary validation (serving a "
                     f"--num-iteration {int(trim)} trim; the canary "
                     "scores the full published model)")
            return None
        import numpy as np
        rows = np.asarray(canary.get("rows"), np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        want = np.asarray(canary.get("scores"),
                          np.float64).reshape(-1)
        tol = float(canary.get("tol", 1e-3))
        # a plain attach — NO buffer donation: the old forest is still
        # serving traffic while the canary runs
        forest = staged.attach()
        got = np.asarray(forest.predict_raw(rows),
                         np.float64).reshape(-1)
        if got.shape != want.shape \
                or not np.allclose(got, want, rtol=0.0, atol=tol):
            worst = (float(np.max(np.abs(got - want)))
                     if got.shape == want.shape else float("inf"))
            if key != self._failed_key:   # once per observed artifact
                from ..resilience.faults import record_fault_event
                record_fault_event(
                    "canary_refused", action="refused_swap",
                    detail=f"canary mismatch on {key[0]}: worst "
                           f"|raw - expected| {worst:.6g} > tol "
                           f"{tol:g} over {int(rows.shape[0])} rows")
            raise ValueError(
                f"canary validation failed: worst |raw - expected| "
                f"{worst:.6g} exceeds tol {tol:g} — the publication "
                "is byte-valid but scores wrong; refusing the swap")
        return forest

    @staticmethod
    def _record_swap_spans(manifest, path: str, model_id,
                           times) -> None:
        """validate -> load -> stage -> apply spans for one successful
        hot swap. The publisher stamped its trace context into the
        manifest (``manifest["trace"]``), so the swap correlates back
        to the publishing generation's trace; an unmanaged artifact
        (no manifest) gets a fresh trace id. Never raises — tracing
        must not fail a completed swap."""
        try:
            from ..obs import trace as _trace
            ctx = (manifest or {}).get("trace") or {}
            tid = ctx.get("trace_id") or _trace.new_trace_id()
            parent = ctx.get("span_id")
            t_poll, t_valid, t_load, t_stage, t_apply = times
            for name, a, b, attrs in (
                    ("swap/validate", t_poll, t_valid, None),
                    ("swap/load", t_valid, t_load, None),
                    ("swap/stage", t_load, t_stage, None),
                    ("swap/apply", t_stage, t_apply,
                     {"model": model_id, "path": path})):
                _trace.record_span(name, a, b, trace_id=tid,
                                   parent_id=parent, attrs=attrs)
        except Exception:
            pass


class _StatsLoop:
    """Periodic ``{"event": "serve"}`` emitter."""

    def __init__(self, state: ServeState, interval_s: float):
        self.state = state
        self.interval_s = max(0.1, float(interval_s))
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="lightgbm-tpu-serve-stats")

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        while not self.state.shutdown_event.wait(self.interval_s):
            self.state.emit_serve_event()


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------

_HELP_EPILOG = """\
The model argument is a model text file, a ckpt_*.npz training
snapshot, or a directory (the newest artifact inside is served and the
directory is watched for hot swaps unless --watch-dir overrides it).
Under `python -m lightgbm_tpu launch N -- python -m lightgbm_tpu serve
...` each rank serves on --port + LIGHTGBM_TPU_RANK and the supervisor
restarts dead replicas. Protocol, swap semantics and telemetry fields:
docs/SERVING.md.

exit codes:
  0  clean shutdown (protocol `shutdown` command or SIGINT)
  1  bad model path / unservable model / socket bind failure
  2  bad command line
"""


def build_parser() -> argparse.ArgumentParser:
    # defaults come from the Config dataclass (the single source of
    # truth docs/PARAMETERS.md renders); importing it is jax-free
    from ..config import Config
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu serve",
        description="JSON-lines inference daemon over a compiled "
                    "forest: shape-bucketed batching (no per-shape "
                    "recompiles), bounded-window micro-batching, "
                    "atomic hot model swap, serve telemetry.",
        epilog=_HELP_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("model",
                   help="model .txt / ckpt_*.npz snapshot / directory")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8799,
                   help="base port; a launch-supervised replica adds "
                        "its rank (default 8799, 0 = ephemeral)")
    p.add_argument("--watch-dir", default=None,
                   help="directory to poll for newer model artifacts "
                        "(atomic hot swap; default: the model "
                        "directory when MODEL is a directory)")
    p.add_argument("--watch-interval", type=float,
                   default=Config.serve_watch_interval_sec,
                   help="watch-dir poll period in seconds")
    p.add_argument("--telemetry", default=None,
                   help="JSONL path for {\"event\": \"serve\"} lines "
                        "(default: $LIGHTGBM_TPU_TELEMETRY)")
    p.add_argument("--stats-interval", type=float,
                   default=Config.serve_stats_interval_sec,
                   help="seconds between serve telemetry events")
    p.add_argument("--window-ms", type=float,
                   default=Config.serve_batch_window_ms,
                   help="micro-batching window in milliseconds")
    p.add_argument("--max-batch-rows", type=int,
                   default=Config.serve_max_batch_rows,
                   help="largest device batch (power of two)")
    p.add_argument("--min-bucket-rows", type=int,
                   default=Config.serve_min_bucket_rows,
                   help="smallest row bucket (power of two)")
    p.add_argument("--queue-rows", type=int,
                   default=Config.serve_queue_rows,
                   help="pending-row budget before submits are "
                        "rejected (backpressure)")
    p.add_argument("--shed-queue-rows", type=int,
                   default=Config.serve_shed_queue_rows,
                   help="soft backlog threshold: above it the batcher "
                        "sheds its OLDEST queued requests with a "
                        "typed {\"shed\": true} reply (0 = disabled)")
    p.add_argument("--shed-p99-ms", type=float,
                   default=Config.serve_shed_p99_ms,
                   help="per-request latency budget: a request that "
                        "already waited longer is shed at dequeue "
                        "time (0 = disabled)")
    p.add_argument("--grace", type=float,
                   default=Config.serve_shutdown_grace_sec,
                   help="graceful-shutdown deadline in seconds: on "
                        "SIGTERM / the shutdown command the daemon "
                        "drains already-accepted requests for up to "
                        "this long before closing")
    p.add_argument("--metrics-port", type=int,
                   default=Config.metrics_port,
                   help="base port of the OpenMetrics /metrics HTTP "
                        "endpoint (obs/export.py); a launch-supervised "
                        "replica adds its rank. 0 disables (default: "
                        "$LIGHTGBM_TPU_METRICS_PORT or off)")
    p.add_argument("--warmup-rows", type=int, default=None,
                   help="pre-compile buckets up to this many rows at "
                        "startup (default: all buckets; 0 disables)")
    p.add_argument("--num-iteration", type=int, default=-1,
                   help="serve only the first N boosting rounds "
                        "(default: all)")
    return p


def _resolve_model(args) -> Tuple[str, Optional[str]]:
    """-> (model path, effective watch dir). jax-free."""
    model = args.model
    watch_dir = args.watch_dir
    if os.path.isdir(model):
        if watch_dir is None:
            watch_dir = model
        found = _find_model_artifact(model)
        if found is None:
            raise FileNotFoundError(
                f"no model artifact (ckpt_*.npz or *.txt) in "
                f"directory {model!r}")
        model = found[1]
    elif not os.path.exists(model):
        raise FileNotFoundError(f"model file not found: {model!r}")
    if watch_dir is not None \
            and not str(watch_dir).startswith("mem://") \
            and not os.path.isdir(watch_dir):
        raise FileNotFoundError(
            f"--watch-dir is not a directory: {watch_dir!r}")
    return model, watch_dir


def main(argv: Optional[List[str]] = None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:       # argparse --help (0) / usage error (2)
        return int(e.code or 0)
    try:
        model_path, watch_dir = _resolve_model(args)
    except (FileNotFoundError, OSError) as e:
        print(f"[LightGBM-TPU] [Fatal] {e}", file=sys.stderr)
        return 1
    # ---- everything below may import jax ----
    rank = int(os.environ.get("LIGHTGBM_TPU_RANK") or 0)
    port = args.port + rank if args.port else 0
    telemetry_path = args.telemetry \
        or os.environ.get("LIGHTGBM_TPU_TELEMETRY")
    if telemetry_path and rank:
        telemetry_path = f"{telemetry_path}.rank{rank}"
    try:
        # key the watch state to the artifact BEFORE loading it (and
        # inside the try: checkpoint rotation can delete/replace the
        # file at any point): stat-then-load can at worst re-swap to
        # identical content on the first poll, while load-then-stat
        # would suppress a legitimate first swap forever
        watch_key = _artifact_key(model_path)
        # a managed artifact (publisher manifest sidecar) is validated
        # at startup exactly like at swap time: serving a torn
        # publication is wrong on boot too, and the exit lets the
        # fleet supervisor retry once the publisher's retry lands
        from ..resilience.publisher import validate_artifact
        manifest = validate_artifact(model_path)
        booster = _load_booster(model_path)
        from .batcher import MicroBatcher
        from .compile import compile_forest
        compile_kwargs = dict(
            num_iteration=args.num_iteration,
            min_bucket=args.min_bucket_rows,
            max_batch_rows=args.max_batch_rows)
        forest = compile_forest(booster, **compile_kwargs)
        if args.warmup_rows != 0:
            forest.warmup(args.warmup_rows)
        # inside the try: bad --window-ms/--queue-rows/bucket values
        # must exit with the documented [Fatal] line, not a traceback
        batcher = MicroBatcher(forest, batch_window_ms=args.window_ms,
                               max_batch_rows=args.max_batch_rows,
                               queue_max_rows=args.queue_rows,
                               shed_queue_rows=args.shed_queue_rows,
                               shed_p99_ms=args.shed_p99_ms)
    except Exception as e:
        print(f"[LightGBM-TPU] [Fatal] cannot serve {model_path!r}: "
              f"{e}", file=sys.stderr)
        return 1
    state = ServeState(batcher, forest.model_id, model_path,
                       telemetry_path=telemetry_path,
                       manifest=manifest)
    try:
        server = _Server((args.host, port), _Handler)
    except OSError as e:
        print(f"[LightGBM-TPU] [Fatal] cannot bind "
              f"{args.host}:{port}: {e}", file=sys.stderr)
        state.close()
        return 1
    server.state = state                     # type: ignore[attr-defined]
    bound_port = server.server_address[1]
    metrics_port = args.metrics_port
    if not metrics_port:
        try:
            metrics_port = int(os.environ.get(
                "LIGHTGBM_TPU_METRICS_PORT") or 0)
        except ValueError:
            metrics_port = 0
    metrics_server = None
    if metrics_port:
        from ..obs.export import ensure_metrics_server
        metrics_server = ensure_metrics_server(
            metrics_port + rank,
            extra_families=state.metrics_families)
    if watch_dir:
        _Watcher(state, watch_dir, args.watch_interval, compile_kwargs,
                 watch_key, args.warmup_rows).start()
    _StatsLoop(state, args.stats_interval).start()
    ready = {"event": "serve_ready", "host": args.host,
             "port": bound_port, "pid": os.getpid(), "rank": rank,
             "model": forest.model_id, "model_source": model_path,
             "watch_dir": watch_dir,
             "metrics_port": None if metrics_server is None
             else metrics_server.port,
             "buckets": forest.buckets()}
    print(json.dumps(ready), flush=True)
    log_info(f"serve: listening on {args.host}:{bound_port} "
             f"(model {forest.model_id}, "
             f"{forest.num_trees} trees, K={forest.K})")
    server_thread = threading.Thread(target=server.serve_forever,
                                     kwargs={"poll_interval": 0.2},
                                     daemon=True,
                                     name="lightgbm-tpu-serve-accept")
    server_thread.start()
    # a supervised restart is a SIGTERM, not a SIGKILL: treat it as a
    # graceful-shutdown request so the drain below still runs and no
    # accepted request is dropped (docs/SERVING.md "Shutdown")
    import signal as _signal
    try:
        _signal.signal(_signal.SIGTERM,
                       lambda *_: state.request_shutdown())
    except ValueError:
        pass      # not the main thread (embedded use): skip the hook
    try:
        # a TIMED wait, not a bare .wait(): the C-level signal flag is
        # only processed by the main thread running bytecode, and a
        # process-directed SIGTERM can be delivered to any thread —
        # the periodic wake guarantees the handler runs even when the
        # kernel picked a worker thread (e.g. a signal queued while
        # the process was SIGSTOPped)
        while not state.shutdown_event.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    # ---- graceful drain (bounded by --grace) ----
    # order matters: flip predict requests to the typed draining
    # refusal first, drain what was already accepted, wait for handler
    # threads to put the replies on the wire — and only THEN stop
    # accepting. Accepting stays open through the drain (plus a short
    # linger) so a connection parked in the kernel's TCP accept
    # backlog at SIGTERM is accepted and answered with
    # {"error": "draining"} instead of being reset by the socket close
    # below. A request the daemon accepted is answered or the client
    # sees the connection close; it is never silently dropped by a
    # supervised restart.
    deadline = time.monotonic() + max(0.0, float(args.grace))
    state.begin_drain()
    state.batcher.close(
        timeout=max(0.1, deadline - time.monotonic()))
    while state.active_handlers() > 0 \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    linger = min(0.5, max(0.0, deadline - time.monotonic()))
    if linger > 0:
        time.sleep(linger)                  # sweep the accept backlog
    server.shutdown()                        # no new connections
    while state.active_handlers() > 0 \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    dropped = state.active_handlers()
    if dropped:
        log_warning(f"serve: {dropped} connection handler(s) still "
                    "busy at the shutdown grace deadline")
    state.emit_serve_event()                 # final snapshot
    server.server_close()
    state.close()
    log_info("serve: shut down cleanly")
    return 0
