"""Exclusive Feature Bundling (FeatureGroup / EFB, feature_group.h:26):
zero-conflict bundles must reproduce the unbundled model exactly, and a
wide sparse matrix must collapse to few bundle columns."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.bundling import build_bundles


def _sparse_onehot(n, groups, per_group, seed=0, noise_feats=2):
    """One-hot blocks (mutually exclusive by construction) + a couple
    of dense features."""
    rs = np.random.RandomState(seed)
    cols = []
    signal = np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        block = np.zeros((n, per_group))
        vals = rs.rand(per_group) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    dense = rs.randn(n, noise_feats)
    X = np.hstack(cols + [dense])
    y = (signal + 0.5 * dense[:, 0]
         + 0.3 * rs.randn(n) > np.median(signal)).astype(float)
    return X, y


def test_build_bundles_collapses_onehot_blocks():
    X, y = _sparse_onehot(4000, groups=6, per_group=8)
    d = lgb.Dataset(X, label=y)
    d.construct()
    info = build_bundles(d.host_bins(), d.mappers)
    assert info is not None
    F = d.num_features()
    G = info.bins_bundled.shape[1]
    assert G < F / 2
    # round-trip: every row/feature bin must be recoverable from its
    # bundle column
    bins = d.host_bins()
    for j in rs_choice(F, 12):
        g = info.bundle_of[j]
        col = info.bins_bundled[:, g].astype(np.int64)
        if info.is_direct[j]:
            rec = col
        else:
            off, nb = int(info.offset_of[j]), d.mappers[j].num_bins
            inside = (col >= off) & (col <= off + nb - 2)
            rec = np.where(inside, col - off + 1, 0)
        np.testing.assert_array_equal(rec, bins[:, j])


def rs_choice(F, k):
    rs = np.random.RandomState(1)
    return rs.choice(F, size=min(k, F), replace=False)


def test_bundled_training_matches_unbundled_exactly():
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=3)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    assert len(plain._models) == len(bundled._models)
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        # leaf values agree up to the f32 rounding of the bin-0
        # reconstruction (total - range); structure is bit-identical
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=5e-3, atol=1e-5)
    np.testing.assert_allclose(plain.predict(X[:200]),
                               bundled.predict(X[:200]),
                               rtol=5e-3, atol=1e-4)


def test_wide_sparse_matrix_trains_with_small_cache():
    """The VERDICT target: a multi-thousand-feature sparse synthetic
    must train with the histogram cache scaled by bundles, not
    features."""
    X, y = _sparse_onehot(3000, groups=160, per_group=25, seed=5)
    assert X.shape[1] == 160 * 25 + 2  # 4002 features
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5}, d,
                    num_boost_round=4)
    info = bst._engine.bundle
    assert info is not None
    # 4002 sparse features must collapse to ~#groups bundle columns
    assert info.bins_bundled.shape[1] < 200
    p = bst.predict(X[:500])
    assert np.all(np.isfinite(p))
    assert np.mean((p > 0.5) == (y[:500] > 0.5)) > 0.7


def test_bundling_skipped_with_dense_data():
    rs = np.random.RandomState(2)
    X = rs.randn(1500, 8)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst._engine.bundle is None


def test_bundling_engages_alongside_nan_feature():
    """A NaN-carrying numeric column must NOT disable bundling for the
    rest of the dataset: it stays a direct singleton (with its dual
    missing-direction scan) while the sparse blocks bundle — and the
    model equals the unbundled one structurally."""
    rs = np.random.RandomState(13)
    n = 2500
    X_blocks, y = _sparse_onehot(n, groups=4, per_group=6, seed=13)
    xnan = rs.randn(n, 1)
    xnan[rs.rand(n) < 0.3] = np.nan
    X = np.hstack([X_blocks, xnan])
    y = ((np.nan_to_num(xnan[:, 0]) > 0.3) ^ (y > 0.5)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_array_equal(
            [ta.default_left(i) for i in range(nn)],
            [tb.default_left(i) for i in range(nn)])
    np.testing.assert_allclose(plain.predict(X[:200]),
                               bundled.predict(X[:200]),
                               rtol=5e-3, atol=1e-4)


def test_nan_members_bundle_and_match_unbundled_exactly():
    """Round 4: NaN-carrying sparse features now JOIN multi-member
    bundles (sparse_bin.hpp:857 coverage): their NaN bin maps to the
    member's last bundle position, is excluded from threshold scans,
    and routes by the learned default direction. The member's bin-0
    mass is reconstructed as total - range_sum (the FixHistogram
    algebra, dataset.h:760), so gains match the unbundled scan only to
    float precision - the checks below are prediction-level parity plus
    structural equality of the FIRST tree (drift accumulates later)."""
    rs = np.random.RandomState(7)
    n = 3000
    X, y = _sparse_onehot(n, groups=5, per_group=7, seed=7)
    # NaN-ify a third of the NONZERO entries of the first two blocks:
    # exclusivity is untouched, but those members now carry NaN bins
    for j in range(14):
        nzr = np.flatnonzero(X[:, j] != 0)
        X[nzr[rs.rand(len(nzr)) < 0.33], j] = np.nan
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=8)
    eng = bundled._engine
    assert eng.bundle is not None, "bundling did not engage"
    # the NaN features must be members of MULTI bundles, not singletons
    multi_members = {j for g in eng.bundle.groups if len(g) > 1
                     for j in g}
    assert any(j in multi_members for j in range(14)), \
        "NaN features were not bundled"
    ta, tb = plain._models[0], bundled._models[0]
    nn = ta.num_nodes
    np.testing.assert_array_equal(ta.split_feature[:nn],
                                  tb.split_feature[:nn])
    np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                  tb.threshold_bin[:nn])
    pp, pb = plain.predict(X), bundled.predict(X)
    # prediction-level parity: same decisions on almost every row
    assert np.mean(np.abs(pp - pb) < 1e-2) > 0.99
    assert np.mean((pp > 0.5) == (pb > 0.5)) > 0.995


def test_allstate_shaped_wide_sparse_with_nan_trains_bundled():
    """Allstate-class shape (round-3 verdict item 5): thousands of
    sparse one-hot features, some carrying NaN, must collapse to a few
    bundle columns (memory << dense [F, n]) and keep accuracy parity
    with the unbundled model."""
    rs = np.random.RandomState(3)
    n, groups, per_group = 4000, 16, 256
    picks = rs.randint(0, per_group, size=(n, groups))
    vals = rs.rand(groups, per_group) * 2
    X = np.zeros((n, groups * per_group), np.float64)
    signal = np.zeros(n)
    for g in range(groups):
        X[np.arange(n), g * per_group + picks[:, g]] = \
            vals[g, picks[:, g]]
        signal += vals[g, picks[:, g]]
    # NaN-ify some nonzero entries of the first block
    for j in range(per_group):
        nzr = np.flatnonzero(X[:, j] != 0)
        X[nzr[rs.rand(len(nzr)) < 0.2], j] = np.nan
    y = (signal > np.median(signal)).astype(float)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5}
    bundled = lgb.train({**params, "num_leaves": 63},
                        lgb.Dataset(X, label=y), num_boost_round=40)
    eng = bundled._engine
    F = groups * per_group
    assert eng.bundle is not None
    G = eng.bundle.bins_bundled.shape[1]
    assert G <= F // 50, (G, F)   # 4096 features -> dozens of columns
    # device matrix is the bundled one: memory scales with G, not F
    assert eng.bins_T.shape[0] == G
    pred = bundled.predict(X)
    acc = np.mean((pred > 0.5) == (y > 0.5))
    assert acc > 0.85, acc


def test_nan_member_boundary_slot_not_stale():
    """A NaN member followed by a NaN-free member shares the boundary
    position (prev's NaN slot == next's t=0 candidate); the next
    member's candidate metadata must NOT inherit the stale NaN pointer
    (round-4 review regression)."""
    rs = np.random.RandomState(21)
    n = 4000
    # two exclusive sparse features: A rich (many bins, NaN), B binary
    pick = rs.randint(0, 6, n)  # 0: A nonzero, 1: B nonzero, else 0
    A = np.where(pick == 0, rs.randint(1, 40, n) / 4.0, 0.0)
    A[(pick == 0) & (rs.rand(n) < 0.4)] = np.nan
    Bcol = np.where(pick == 1, 1.0, 0.0)
    X = np.column_stack([A, Bcol, rs.randn(n), rs.randn(n)])
    y = ((np.nan_to_num(A) + Bcol + 0.3 * X[:, 2]) >
         0.8).astype(float)
    d = lgb.Dataset(X, label=y)
    d.construct()
    info = build_bundles(d.host_bins(), d.mappers)
    assert info is not None
    ga, gb = info.bundle_of[0], info.bundle_of[1]
    assert ga == gb and not info.is_direct[0], "A+B did not bundle"
    # whichever member comes second: its t=0 slot (off-1) must carry
    # ITS OWN nan pointer (-1 for the NaN-free member), never the
    # neighbor's
    for j in (0, 1):
        off = int(info.offset_of[j])
        own_nan = d.mappers[j].missing_type == "nan"
        got = int(info.nanpos_at[ga, off - 1])
        if own_nan:
            nb = d.mappers[j].num_bins
            assert got == ga * info.num_positions + off + nb - 2, got
        else:
            assert got == -1, got
    # and end-to-end: bundled tracks unbundled (deep noise-feature
    # near-ties may flip under the FixHistogram float algebra, so the
    # check is decision-level)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=4)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=4)
    pp, pb = plain.predict(X), bundled.predict(X)
    assert np.mean((pp > 0.5) == (pb > 0.5)) > 0.995


def _mixed_cat_onehot(n, groups=3, per_group=6, seed=9):
    """Numerical one-hot blocks + a sparse small categorical (one-hot
    regime, bundles) + a sparse wide categorical (sorted-subset
    regime, stays a direct singleton) + dense numerics."""
    rs = np.random.RandomState(seed)
    X, y = _sparse_onehot(n, groups, per_group, seed=seed)
    # two small cats with DISJOINT tail supports so they are mutually
    # exclusive and can bundle with each other (a full one-hot block's
    # union covers every row, so nothing else fits those bundles)
    u = rs.rand(n)
    small_a = np.full(n, 7.0)
    ta = u < 0.12
    small_a[ta] = rs.choice([1, 2, 3], size=int(ta.sum()))
    small_b = np.zeros(n)
    tb = (u >= 0.5) & (u < 0.62)
    small_b[tb] = rs.choice([4, 5], size=int(tb.sum()))
    # wide cat: dominant 0 (~84%), tail 1..9 — stays a direct column
    wide = np.zeros(n)
    tailw = rs.rand(n) < 0.16
    wide[tailw] = rs.randint(1, 10, size=int(tailw.sum()))
    Xm = np.column_stack([X, small_a, small_b, wide])
    y = ((y > 0) ^ (small_a == 2) ^ (small_b == 5)
         ^ ((wide >= 5) & tailw)).astype(float)
    cat_idx = [X.shape[1], X.shape[1] + 1, X.shape[1] + 2]
    return Xm, y, cat_idx


def test_categorical_members_bundle_and_match_unbundled():
    """Categorical EFB members (VERDICT r4 #7): type-blind bundling
    like FindGroups (dataset.cpp). Small cats (one-hot regime) join
    bundles with candidate-exact parity; wide cats stay direct
    singleton columns where the sorted-subset scan runs verbatim.

    Contract: the candidate SETS are exact, but a bundled member's
    bin-0 stats are reconstructed as total - range in f32 (the
    FixHistogram algebra), so gains can differ in the ~5th digit and
    near-tie leaf-EXPANSION ORDER may permute node numbering (same
    caveat the numeric NaN-member test documents). Assert
    order-invariant equality: per-tree leaf counts, per-tree split
    multisets, and prediction parity."""
    X, y, cat_idx = _mixed_cat_onehot(4000)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "categorical_feature": cat_idx}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=8)
    info = bundled._engine.bundle
    assert info is not None, "bundling did not engage"
    # the small cat must actually be INSIDE a multi-member bundle
    small_cat_used = cat_idx[0]
    in_multi = any(small_cat_used in g and len(g) > 1
                   for g in info.groups)
    assert in_multi, "small categorical did not join a bundle"
    assert len(plain._models) == len(bundled._models)
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert sorted(ta.split_feature[:nn]) ==             sorted(tb.split_feature[:nn])
        np.testing.assert_allclose(
            np.sort(ta.leaf_value[:ta.num_leaves]),
            np.sort(tb.leaf_value[:tb.num_leaves]),
            rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(plain.predict(X[:400]),
                               bundled.predict(X[:400]),
                               rtol=2e-3, atol=2e-3)


def test_categorical_member_split_categories_correct():
    """A bundled cat member's splits must route ORIGINAL category
    values (not remapped bundle positions): with a label that depends
    only on the two bundled cats' categories, the bundled model must
    isolate them perfectly and agree with the unbundled model."""
    rs = np.random.RandomState(3)
    n = 4000
    u = rs.rand(n)
    cat_a = np.full(n, 7.0)
    ta = u < 0.2
    cat_a[ta] = rs.choice([1, 2, 3], size=int(ta.sum()))
    cat_b = np.zeros(n)
    tb = (u >= 0.5) & (u < 0.7)
    cat_b[tb] = rs.choice([4, 5], size=int(tb.sum()))
    noise = rs.randn(n, 2)
    X = np.column_stack([cat_a, cat_b, noise])
    y = ((cat_a == 2) | (cat_b == 5)).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "categorical_feature": [0, 1]}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=20)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=20)
    info = bundled._engine.bundle
    assert info is not None and any(len(g) > 1 for g in info.groups)
    pb = bundled.predict(X)
    assert np.mean((pb > 0.5) == (y > 0.5)) > 0.99
    np.testing.assert_allclose(pb, plain.predict(X),
                               rtol=2e-3, atol=2e-3)


def test_bundled_interaction_constraints_match_unbundled():
    """interaction_constraints x EFB (round 5): the constraint masks
    and branch sets live in ORIGINAL feature space regardless of
    bundling, so constrained training must produce the same trees
    bundled and unbundled — and must never split across groups."""
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=21)
    F = X.shape[1]
    g1 = list(range(0, 12))           # blocks 0-1
    g2 = list(range(12, F))           # blocks 2-3 + dense
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5,
              "interaction_constraints": [g1, g2]}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
    # constraint actually honored: no root-to-leaf path mixes groups
    for t in bundled._models:
        nn = t.num_nodes
        used = set(int(f) for f in t.split_feature[:nn])
        # per-tree check is necessary but loose; walk each path
        def walk(node, seen):
            if node < 0:
                return
            f = int(t.split_feature[node])
            seen = seen | {f}
            assert all(x < 12 for x in seen) or \
                all(x >= 12 for x in seen), seen
            walk(int(t.left_child[node]), seen)
            walk(int(t.right_child[node]), seen)
        if nn:
            walk(0, set())


def test_bundled_bynode_sampling_matches_unbundled():
    """feature_fraction_bynode x EFB (round 5): the per-node keyed
    draw samples ORIGINAL features (F_orig, not bundle columns), so
    the sampled masks — and therefore the trees — are identical
    bundled and unbundled."""
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=22)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "feature_fraction_bynode": 0.6,
              "feature_fraction_seed": 7}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_array_equal(ta.leaf_count[:ta.num_leaves],
                                      tb.leaf_count[:tb.num_leaves])
        # mask parity is fully covered by the exact structure/count
        # checks above; leaf VALUES only agree to the f32 rounding of
        # the bundled bin-0 reconstruction (total - range, the
        # FixHistogram algebra) — ~2e-3 relative on this seed, same
        # class and bound as test_bundled_training_matches_unbundled_
        # exactly. The original 2e-4 tolerance asserted exactness the
        # bundled leaf-stat algebra never promised (root-caused: all 6
        # trees structure-identical at seed, drift present from tree 0,
        # i.e. not split-divergence accumulation).
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=5e-3, atol=1e-5)


def test_bundled_cegb_matches_unbundled():
    """CEGB x EFB (round 5): the per-feature penalties (split /
    coupled first-use / lazy per-row acquisition) are [F_orig]-space
    quantities consumed through the position->member map
    (gain_penalty[member_ix]), so CEGB-regularized training must
    produce the same trees bundled and unbundled."""
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=25)
    F = X.shape[1]
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5,
              "cegb_penalty_split": 1e-4,
              "cegb_penalty_feature_coupled": [0.5] * F,
              "cegb_penalty_feature_lazy": [1e-3] * F,
              "cegb_tradeoff": 1.0}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    assert bundled._engine.cegb_enabled
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=2e-4, atol=2e-4)



def test_bundled_path_smoothing_matches_unbundled():
    """path_smooth x EFB (round 5): smoothed outputs/gains flow
    through the bundled eval exactly like the plain eval_dir."""
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=28)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "path_smooth": 5.0}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=2e-4, atol=2e-4)


def test_bundled_forced_splits_match_unbundled(tmp_path):
    """forcedsplits x EFB (round 5): a forced (feature, bin) split on
    a bundled MEMBER reconstructs its left stats from the bundle
    column (total - member range); trees must match unbundled."""
    import json
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=29)
    # force the root on member feature 0 at a threshold inside bin 0
    # (zeros left, its one-hot value right); then free growth
    path = tmp_path / "forced.json"
    path.write_text(json.dumps({"feature": 0, "threshold": 0.0}))
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5,
              "forcedsplits_filename": str(path)}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=5)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert int(ta.split_feature[0]) == 0
        assert int(tb.split_feature[0]) == 0
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=2e-4, atol=2e-4)



@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
def test_bundled_monotone_matches_unbundled(method):
    """monotone x EFB, all three methods (round 5): basic/intermediate
    use scalar per-leaf bounds, advanced ('monotone precise') gathers
    its [F_orig, B] per-threshold bound arrays into candidate space
    via (member_at, tloc_at). Constrained training must match the
    unbundled model tree-exactly, and the monotone property must hold
    on the bundled model."""
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=27)
    F = X.shape[1]
    mono = [0] * F
    mono[0], mono[7], mono[F - 2] = 1, -1, 1   # two members + a dense
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "monotone_constraints": mono,
              "monotone_constraints_method": method}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=2e-4, atol=2e-4)
    probe = np.zeros((50, F))
    probe[:, 0] = np.linspace(0, 2, 50)
    pred = bundled.predict(probe)
    assert np.all(np.diff(pred) >= -1e-7)


def test_bundled_advanced_monotone_with_cat_and_nan_members():
    """advanced monotone x EFB with categorical AND NaN-carrying
    bundle members present: exercises the cat candidates' scalar
    bound fallbacks (bounds_c / the is_cat_win winner-bounds branch)
    and the NaN members' tloc gather alongside the advanced bound
    arrays. Cat near-tie rounding permutes expansion order, so the
    contract is order-invariant (split multisets + predictions)."""
    X, y, cat_idx = _mixed_cat_onehot(4000, seed=14)
    rs = np.random.RandomState(6)
    X = X.copy()
    X[rs.rand(len(X)) < 0.08, 1] = np.nan     # NaN-carrying member
    F = X.shape[1]
    mono = [0] * F
    mono[0], mono[3] = 1, -1                  # numeric members only
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "categorical_feature": cat_idx,
              "monotone_constraints": mono,
              "monotone_constraints_method": "advanced"}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert sorted(ta.split_feature[:nn]) == \
            sorted(tb.split_feature[:nn])
    np.testing.assert_allclose(plain.predict(X[:400]),
                               bundled.predict(X[:400]),
                               rtol=2e-3, atol=2e-3)
    probe = np.zeros((50, F))
    probe[:, 0] = np.linspace(0, 2, 50)
    pred = bundled.predict(probe)
    assert np.all(np.diff(pred) >= -1e-7)
