"""Single source of truth for the cross-process wire contracts.

The fleet (trainer ranks, serve replicas, the elastic and pipeline
supervisors, the publisher) speaks exactly four stringly-typed
languages: JSONL ``{"event": ...}`` records, metrics-registry family
names, ``LIGHTGBM_TPU_*`` environment variables, and fault-kind
strings.  Every one of those names is DECLARED here — and only here.

- The runtime imports its key tuples from this module
  (``obs/recorder.py``'s ``ITERATION_EVENT_KEYS``, ``obs/trace.py``'s
  ``SPAN_EVENT_KEYS``, ``resilience/faults.py``'s ``_KNOWN_KINDS``,
  ``resilience/elastic.py``'s ``_ONE_SHOT_KINDS`` are all re-exports).
- The contract lint (``analysis/rules_contract.py``, TPL015-TPL018)
  literal-evals the registry dicts below straight out of this file's
  AST and verifies every emission, bump, read, and injection site in
  the package against them — which is why the five registry dicts
  MUST stay pure literals (no comprehensions, no calls, no names).
  Derived conveniences live below the literals.
- ``tools/gen_obs_docs.py`` renders docs/OBSERVABILITY.md's event /
  metric / env-var tables from these dicts; the lint flags drift.

Jax-free by construction: the default ``lint`` path, the serve
daemon's jax-free supervisors, and the docs generator all import this
module on hosts where no backend can initialize.
"""

from __future__ import annotations

__all__ = ["EVENTS", "METRICS", "EXPORT_FAMILIES", "ENV_VARS",
           "FAULT_KINDS", "FAULT_EVENT_KINDS", "EVENT_NAMES",
           "event_keys", "required_keys", "one_shot_fault_kinds",
           "injectable_fault_kinds", "fault_event_kinds"]

# ---------------------------------------------------------------------
# 1. JSONL events: name -> required/optional key sets
# ---------------------------------------------------------------------
# ``required`` keys are present on EVERY line of the event (in this
# order for events whose writer builds the dict from the tuple);
# ``optional`` keys may appear (``**stats``-style spreads, manifest
# payloads, degraded modes). A consumer may only reference declared
# keys; an emitter may only emit declared events and keys (TPL015).

EVENTS = {
    "iteration": {
        "doc": "one line per boosting iteration "
               "(obs/recorder.py record_iteration)",
        "required": ("event", "iteration", "wall_time", "phases",
                     "recompiles", "hbm", "tree", "eval", "comm",
                     "scan"),
        "optional": (),
    },
    "ingest": {
        "doc": "one line per streamed-ingest build "
               "(data/ingest.py two-pass pipeline)",
        "required": ("event",),
        "optional": ("rows", "features", "used_features", "chunks",
                     "chunk_rows", "sample_rows", "pass1_s", "pass2_s",
                     "host_binned_bytes", "source", "world",
                     "label_hash"),
    },
    "fault": {
        "doc": "one line per injected or observed fault "
               "(resilience/faults.py append_fault_event)",
        "required": ("event", "kind", "iteration", "action", "detail",
                     "time"),
        "optional": (),
    },
    "compile": {
        "doc": "one line per XLA compile with cost attribution "
               "(obs/cost.py)",
        "required": ("event", "entry", "signature", "flops",
                     "bytes_accessed", "wall_ms", "compiles",
                     "device_kind", "peak_flops", "peak_bytes_per_sec",
                     "optimal_ms", "time"),
        "optional": (),
    },
    "span": {
        "doc": "one distributed-tracing span "
               "(obs/trace.py make_span)",
        "required": ("event", "name", "trace_id", "span_id",
                     "parent_id", "wall", "mono", "dur", "proc",
                     "attrs"),
        "optional": (),
    },
    "serve": {
        "doc": "periodic serve-daemon stats snapshot "
               "(serve/daemon.py emit_serve_event)",
        "required": ("event",),
        "optional": ("queue_depth_rows", "requests_total", "rows_total",
                     "batches_total", "swaps_total", "rejected_total",
                     "shed_total", "shed_rows", "p50_ms", "p99_ms",
                     "model", "model_source", "manifest",
                     "swap_failures", "shed_replies", "draining",
                     "uptime_s", "qps", "rows_per_sec", "recompiles",
                     "hbm"),
    },
    "serve_ready": {
        "doc": "serve-daemon startup handshake on stdout "
               "(serve/daemon.py main)",
        "required": ("event", "host", "port", "pid", "rank", "model",
                     "model_source", "watch_dir", "metrics_port",
                     "buckets"),
        "optional": (),
    },
    "publish": {
        "doc": "one line per atomic model publication; the manifest "
               "rides along (resilience/publisher.py, pipeline.py)",
        "required": ("event",),
        "optional": ("file", "sha256", "generation", "train_auc",
                     "size_bytes", "trees", "time", "canary",
                     "model_id", "attempts"),
    },
    "published": {
        "doc": "publisher CLI success line on stdout (pipeline.py "
               "publish_generation)",
        "required": ("event", "generation", "file", "sha256",
                     "train_auc"),
        "optional": (),
    },
    "fleet": {
        "doc": "one supervisor scrape over replica or rank /metrics "
               "endpoints (resilience/elastic.py)",
        "required": ("event", "shape", "time"),
        "optional": ("replicas", "restarts_total", "nprocs", "ranks",
                     "iteration_skew"),
    },
    "autoscale": {
        "doc": "one line per fleet scaling action "
               "(resilience/elastic.py)",
        "required": ("event", "action", "rank", "replicas", "reason",
                     "time"),
        "optional": (),
    },
    "rollback": {
        "doc": "one line per canary/health-ordered publication "
               "rollback (resilience/elastic.py)",
        "required": ("event", "bad_file", "bad_sha", "good_file",
                     "good_sha", "time"),
        "optional": (),
    },
    "client": {
        "doc": "load-generator client-side view "
               "(pipeline.py LoadGenerator)",
        "required": ("event", "time"),
        "optional": ("attempts", "ok", "shed", "overloaded", "draining",
                     "error", "conn", "timeout", "max_ok_gap_s",
                     "model", "since_last_ok_s", "p50_ms", "p99_ms"),
    },
    "pipeline": {
        "doc": "pipeline-supervisor lifecycle phase marker "
               "(pipeline.py)",
        "required": ("event", "phase", "time"),
        "optional": ("generation", "generations", "rc", "trace_id",
                     "rate", "ports", "replicas", "max_replicas",
                     "warm_start", "fault_inject", "sha256", "bad_sha",
                     "good_sha", "good_file"),
    },
    "pipeline_summary": {
        "doc": "the pipeline run's final scorecard (pipeline.py "
               "_finish)",
        "required": ("event", "generations_requested",
                     "generations_published", "swaps_confirmed",
                     "rollbacks", "last_published_sha256",
                     "last_published_generation",
                     "train_auc_by_generation", "failures", "time"),
        "optional": ("fleet", "fleet_lifecycle", "client"),
    },
}

# ---------------------------------------------------------------------
# 2. metrics-registry families: name -> kind + label names
# ---------------------------------------------------------------------
# Every ``registry.counter/gauge/histogram`` / ``bump_counter`` call
# in the package must name a family declared here with the declared
# kind and label set; declared-but-never-bumped families are lint
# findings too (TPL016).

METRICS = {
    # training loop (obs/recorder.py _feed_registry)
    "iterations": {
        "kind": "counter", "labels": (),
        "doc": "boosting iterations recorded"},
    "jit_recompiles": {
        "kind": "counter", "labels": (),
        "doc": "XLA recompiles observed by the recompile watcher"},
    "phase_seconds": {
        "kind": "histogram", "labels": ("phase",),
        "doc": "per-iteration Timer phase seconds"},
    "hbm_bytes_in_use": {
        "kind": "gauge", "labels": (),
        "doc": "device HBM bytes in use after the iteration"},
    "hbm_peak_bytes_in_use": {
        "kind": "gauge", "labels": (),
        "doc": "device HBM peak bytes in use"},
    "tree_leaves": {
        "kind": "histogram", "labels": (),
        "doc": "leaves per finished tree"},
    "tree_split_gain_sum": {
        "kind": "histogram", "labels": (),
        "doc": "summed split gain per finished tree"},
    "comm_bytes": {
        "kind": "counter", "labels": ("mode", "wire"),
        "doc": "collective payload bytes by parallelism mode and "
               "hist_comm wire format"},
    "fused_scan_iterations": {
        "kind": "counter", "labels": (),
        "doc": "iterations that ran inside a fused scan window"},
    "fused_scan_windows": {
        "kind": "counter", "labels": (),
        "doc": "fused scan windows dispatched (models/gbdt.py)"},
    # ingestion (data/ingest.py, basic.py, parallel/placement.py)
    "ingest_chunks": {
        "kind": "counter", "labels": (),
        "doc": "row chunks streamed through two-pass ingestion"},
    "ingest_rows": {
        "kind": "counter", "labels": (),
        "doc": "rows streamed through two-pass ingestion"},
    "host_binned_bytes": {
        "kind": "gauge", "labels": (),
        "doc": "host footprint of this rank's binned shard (drops to "
               "~0 after device placement)"},
    # distributed init + collectives (parallel/, resilience/watchdog)
    "init_retries": {
        "kind": "counter", "labels": (),
        "doc": "distributed-init connection retries"},
    "init_backoff_seconds": {
        "kind": "counter", "labels": (),
        "doc": "seconds slept in distributed-init backoff"},
    "collective_timeouts": {
        "kind": "counter", "labels": (),
        "doc": "host collectives aborted by the watchdog deadline"},
    # faults (resilience/faults.py)
    "fault_events": {
        "kind": "counter", "labels": ("kind",),
        "doc": "fault events recorded, by kind"},
    # XLA cost attribution (obs/cost.py)
    "xla_compiles": {
        "kind": "counter", "labels": ("entry",),
        "doc": "XLA compiles per jit entry point"},
    "xla_compile_ms": {
        "kind": "histogram", "labels": ("entry",),
        "doc": "per-compile wall ms per entry point"},
    "xla_flops": {
        "kind": "gauge", "labels": ("entry",),
        "doc": "cost-model flops of the newest compiled program"},
    "xla_bytes_accessed": {
        "kind": "gauge", "labels": ("entry",),
        "doc": "cost-model bytes accessed of the newest compiled "
               "program"},
    # serve daemon (serve/daemon.py)
    "serve_swaps": {
        "kind": "counter", "labels": (),
        "doc": "hot model swaps completed"},
    "serve_swap_failures": {
        "kind": "counter", "labels": (),
        "doc": "hot model swaps refused or failed"},
    "serve_shed_requests": {
        "kind": "counter", "labels": (),
        "doc": "requests shed by the admission gate"},
    "serve_queue_depth_rows": {
        "kind": "gauge", "labels": (),
        "doc": "rows queued in the batcher"},
    # publisher (resilience/publisher.py)
    "publish_total": {
        "kind": "counter", "labels": (),
        "doc": "successful atomic publications"},
    "publish_retries": {
        "kind": "counter", "labels": (),
        "doc": "publication attempts retried"},
    "publish_backoff_seconds": {
        "kind": "counter", "labels": (),
        "doc": "seconds slept in publish retry backoff"},
    "publish_failures": {
        "kind": "counter", "labels": (),
        "doc": "publications that exhausted their retry budget"},
    "publish_pruned": {
        "kind": "counter", "labels": (),
        "doc": "superseded artifacts pruned from the store"},
    "publish_rollbacks": {
        "kind": "counter", "labels": (),
        "doc": "publications rolled back to last-known-good"},
    # supervisors (resilience/elastic.py)
    "supervisor_restarts": {
        "kind": "counter", "labels": (),
        "doc": "worker restarts by the single-rank supervisor"},
    "supervisor_backoff_seconds": {
        "kind": "counter", "labels": (),
        "doc": "seconds slept in supervisor restart backoff"},
    "elastic_restarts": {
        "kind": "counter", "labels": (),
        "doc": "whole-world restarts by the elastic supervisor"},
    "fleet_scale_ups": {
        "kind": "counter", "labels": (),
        "doc": "autoscale scale-up actions"},
    "fleet_scale_downs": {
        "kind": "counter", "labels": (),
        "doc": "autoscale scale-down actions"},
    "fleet_rollbacks": {
        "kind": "counter", "labels": (),
        "doc": "publication rollbacks ordered by the fleet guard"},
    "fleet_replicas_active": {
        "kind": "gauge", "labels": (),
        "doc": "serve replicas currently live"},
    "fleet_replica_up": {
        "kind": "gauge", "labels": ("replica",),
        "doc": "1 when the replica answered its last scrape"},
    "fleet_replica_restarts": {
        "kind": "gauge", "labels": ("replica",),
        "doc": "restarts of the replica so far"},
    "fleet_replica_qps": {
        "kind": "gauge", "labels": ("replica",),
        "doc": "replica requests/s at the last scrape"},
    "fleet_replica_p99_ms": {
        "kind": "gauge", "labels": ("replica",),
        "doc": "replica p99 latency ms at the last scrape"},
    "fleet_replica_shed": {
        "kind": "gauge", "labels": ("replica",),
        "doc": "replica shed total at the last scrape"},
    "fleet_rank_up": {
        "kind": "gauge", "labels": ("rank",),
        "doc": "1 when the training rank answered its last scrape"},
    "fleet_rank_iterations": {
        "kind": "gauge", "labels": ("rank",),
        "doc": "the rank's iteration counter at the last scrape"},
    "fleet_iteration_skew": {
        "kind": "gauge", "labels": (),
        "doc": "max-min iteration spread across live ranks"},
}

# ---------------------------------------------------------------------
# 2b. rendered-only OpenMetrics families (obs/export.py extra_families)
# ---------------------------------------------------------------------
# These appear on /metrics but are computed per scrape from live
# snapshots, never stored in the registry; declared so the docs table
# and the fleet scraper's sample names stay honest.

EXPORT_FAMILIES = {
    "serve_requests": {
        "kind": "counter",
        "doc": "requests accepted by the serve daemon"},
    "serve_rows": {
        "kind": "counter", "doc": "rows predicted"},
    "serve_batches": {
        "kind": "counter", "doc": "device batches dispatched"},
    "serve_rejected": {
        "kind": "counter", "doc": "malformed requests rejected"},
    "serve_shed": {
        "kind": "counter", "doc": "requests shed under overload"},
    "serve_shed_rows": {
        "kind": "counter", "doc": "rows shed under overload"},
    "serve_queue_depth_rows": {
        "kind": "gauge", "doc": "rows queued in the batcher"},
    "serve_p50_ms": {
        "kind": "gauge", "doc": "p50 request latency ms"},
    "serve_p99_ms": {
        "kind": "gauge", "doc": "p99 request latency ms"},
    "serve_qps": {
        "kind": "gauge", "doc": "requests/s over the stats window"},
    "serve_rows_per_sec": {
        "kind": "gauge", "doc": "rows/s over the stats window"},
    "serve_model_info": {
        "kind": "gauge",
        "doc": "always 1; model id and publication sha ride the "
               "labels"},
    "hbm_bytes_in_use": {
        "kind": "gauge", "doc": "device HBM bytes in use"},
    "hbm_peak_bytes_in_use": {
        "kind": "gauge", "doc": "device HBM peak bytes"},
    "client_attempts": {
        "kind": "counter", "doc": "load-generator request attempts"},
    "client_ok": {
        "kind": "counter", "doc": "load-generator successes"},
    "client_shed": {
        "kind": "counter", "doc": "replies shed by the daemon"},
    "client_overloaded": {
        "kind": "counter", "doc": "overloaded replies"},
    "client_draining": {
        "kind": "counter", "doc": "draining replies"},
    "client_error": {
        "kind": "counter", "doc": "error replies"},
    "client_conn": {
        "kind": "counter", "doc": "connection failures"},
    "client_timeout": {
        "kind": "counter", "doc": "request timeouts"},
    "client_p50_ms": {
        "kind": "gauge", "doc": "client-side p50 latency ms"},
    "client_p99_ms": {
        "kind": "gauge", "doc": "client-side p99 latency ms"},
    "client_max_ok_gap_s": {
        "kind": "gauge", "doc": "longest gap between successes"},
    "client_since_last_ok_s": {
        "kind": "gauge", "doc": "seconds since the last success"},
}

# ---------------------------------------------------------------------
# 3. LIGHTGBM_TPU_* environment variables
# ---------------------------------------------------------------------
# ``default`` is the string every ``environ.get`` site must claim
# (None: the variable has no default — read sites must not invent
# one; that is exactly the multi-site-default drift TPL017 exists to
# catch). ``kind`` is documentation (flag/int/float/str/path/spec).

ENV_VARS = {
    "LIGHTGBM_TPU_RANK": {
        "default": None, "kind": "int",
        "doc": "this process's rank; exported by the supervisors, "
               "read by distributed init, telemetry labels, serve "
               "and fault gating (unset: single-process)"},
    "LIGHTGBM_TPU_NUM_PROCS": {
        "default": None, "kind": "int",
        "doc": "world size for explicit-env distributed init"},
    "LIGHTGBM_TPU_COORDINATOR": {
        "default": None, "kind": "str",
        "doc": "host:port of the jax.distributed coordinator"},
    "LIGHTGBM_TPU_RESTART_COUNT": {
        "default": None, "kind": "int",
        "doc": "elastic-supervisor generation counter exported to "
               "workers (0 on first launch)"},
    "LIGHTGBM_TPU_TELEMETRY": {
        "default": None, "kind": "path",
        "doc": "JSONL telemetry stream path; rank N appends .rankN, "
               "the fleet supervisor appends .fleet"},
    "LIGHTGBM_TPU_METRICS_PORT": {
        "default": None, "kind": "int",
        "doc": "OpenMetrics /metrics port; worker rank r binds "
               "port+r (supervisors export base+1)"},
    "LIGHTGBM_TPU_TIMETAG": {
        "default": "", "kind": "flag",
        "doc": "enable the phase Timer ('' or '0': disabled)"},
    "LIGHTGBM_TPU_TRACE_TO": {
        "default": None, "kind": "path",
        "doc": "jax profiler trace output directory"},
    "LIGHTGBM_TPU_XPROF": {
        "default": None, "kind": "spec",
        "doc": "xprof capture spec for the bench harness"},
    "LIGHTGBM_TPU_TRACE_CTX": {
        "default": None, "kind": "spec",
        "doc": "trace_id:span_id inherited by spawned workers so "
               "their spans join the parent trace"},
    "LIGHTGBM_TPU_COST_ATTRIBUTION": {
        "default": "1", "kind": "flag",
        "doc": "record per-compile XLA cost events ('0': off)"},
    "LIGHTGBM_TPU_COST_OPTIMIZED": {
        "default": "", "kind": "flag",
        "doc": "assert the cost-model roofline in bench mode"},
    "LIGHTGBM_TPU_PEAK_TFLOPS": {
        "default": None, "kind": "float",
        "doc": "override the device peak TFLOP/s for the roofline"},
    "LIGHTGBM_TPU_PEAK_GBPS": {
        "default": None, "kind": "float",
        "doc": "override the device peak HBM GB/s for the roofline"},
    "LIGHTGBM_TPU_CHECKPOINT": {
        "default": None, "kind": "path",
        "doc": "checkpoint directory; implies auto-checkpoint and "
               "auto-resume"},
    "LIGHTGBM_TPU_CHECKPOINT_EVERY": {
        "default": "1", "kind": "int",
        "doc": "checkpoint cadence in iterations"},
    "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": {
        "default": None, "kind": "float",
        "doc": "host-collective watchdog deadline seconds (overrides "
               "Config.collective_timeout_sec; 0 disables)"},
    "LIGHTGBM_TPU_FAULT_INJECT": {
        "default": "", "kind": "spec",
        "doc": "comma list of kind@iteration chaos tokens "
               "(docs/RESILIENCE.md)"},
    "LIGHTGBM_TPU_FAULT_RANK": {
        "default": "0", "kind": "spec",
        "doc": "comma list of ranks distributed faults fire on"},
    "LIGHTGBM_TPU_INIT_RETRIES": {
        "default": "10", "kind": "int",
        "doc": "distributed-init connection attempts"},
    "LIGHTGBM_TPU_INIT_BACKOFF": {
        "default": "0.5", "kind": "float",
        "doc": "base seconds of distributed-init backoff"},
    "LIGHTGBM_TPU_INIT_TIMEOUT": {
        "default": None, "kind": "float",
        "doc": "per-attempt distributed-init timeout seconds"},
    "LIGHTGBM_TPU_HOSTSYNC": {
        "default": "auto", "kind": "str",
        "doc": "host collective transport: auto/jax/tcp"},
    "LIGHTGBM_TPU_COMM_EXCHANGE": {
        "default": None, "kind": "flag",
        "doc": "force the two-phase comm exchange path"},
    "LIGHTGBM_TPU_DISABLE_PALLAS": {
        "default": "", "kind": "flag",
        "doc": "'1': never use the Pallas histogram kernel"},
    "LIGHTGBM_TPU_AUTO_PALLAS": {
        "default": None, "kind": "flag",
        "doc": "'1': let the cost model flip the Pallas kernel on"},
    "LIGHTGBM_TPU_DISABLE_SCAN": {
        "default": None, "kind": "flag",
        "doc": "'1': force per-iteration dispatch (no fused scan)"},
    "LIGHTGBM_TPU_AUTO_SCAN_ITERS": {
        "default": "", "kind": "spec",
        "doc": "override the fused-scan auto window, e.g. '8'"},
    "LIGHTGBM_TPU_FORCE_DONATE": {
        "default": None, "kind": "flag",
        "doc": "'1': keep donation declared even where the runtime "
               "would reject it (IR lint lowering)"},
    "LIGHTGBM_TPU_DEBUG_GATHER": {
        "default": None, "kind": "flag",
        "doc": "debug-check gather indices on host first"},
    "LIGHTGBM_TPU_BUILD_DIR": {
        "default": None, "kind": "path",
        "doc": "native extension build directory override"},
    "LIGHTGBM_TPU_NO_NATIVE": {
        "default": None, "kind": "flag",
        "doc": "non-empty: never load the native extension"},
}

# ---------------------------------------------------------------------
# 4. fault kinds
# ---------------------------------------------------------------------
# Injectable kinds (LIGHTGBM_TPU_FAULT_INJECT tokens). ``one_shot``
# kinds are stripped from the env var after a supervised restart
# (resilience/elastic.py): re-injecting a kill on every generation
# would restart-loop the world forever.

FAULT_KINDS = {
    "nan_grad": {
        "one_shot": False,
        "doc": "poison iteration N's gradients with NaN"},
    "nan_hess": {
        "one_shot": False,
        "doc": "poison iteration N's hessians with NaN"},
    "oom": {
        "one_shot": False,
        "doc": "synthetic RESOURCE_EXHAUSTED at iteration N"},
    "kill": {
        "one_shot": False,
        "doc": "SIGKILL this process at iteration N"},
    "rank_kill": {
        "one_shot": True,
        "doc": "SIGKILL the LIGHTGBM_TPU_FAULT_RANK rank(s) at "
               "iteration N (-1: during ingest)"},
    "stall_rank": {
        "one_shot": True,
        "doc": "infinite stall on the selected rank(s) at iteration "
               "N (watchdog fodder)"},
    "init_refuse": {
        "one_shot": False,
        "doc": "refuse N distributed-init connection attempts"},
    "publish_torn": {
        "one_shot": False,
        "doc": "leave a torn artifact on generation G's publish "
               "attempt"},
    "publish_poison": {
        "one_shot": False,
        "doc": "publish a sha-valid but canary-poisoned model"},
    "store_outage": {
        "one_shot": False,
        "doc": "artifact-store outage on generation G's publish "
               "attempt"},
    "serve_kill": {
        "one_shot": True,
        "doc": "SIGKILL the serve daemon at its N-th accepted "
               "request"},
    "refit_nan": {
        "one_shot": False,
        "doc": "poison tree T's gradients during Booster.refit"},
}

# Observed-only fault-EVENT kinds: never injectable, but emitted as
# ``{"event": "fault"}`` lines (and ``fault_events{kind}`` bumps) when
# the resilience layer trips on a real condition.

FAULT_EVENT_KINDS = {
    "nonfinite": {
        "doc": "the non-finite guard tripped on real grads/hessians"},
    "collective_timeout": {
        "doc": "a host collective missed the watchdog deadline"},
    "collective_error": {
        "doc": "a host collective raised (transport error)"},
    "swap_failure": {
        "doc": "a serve hot-swap failed; the old model keeps serving"},
    "canary_refused": {
        "doc": "the serve-side canary gate refused a publication"},
}

# ---------------------------------------------------------------------
# derived conveniences (NOT literal-evaled by the lint)
# ---------------------------------------------------------------------

EVENT_NAMES = frozenset(EVENTS)


def event_keys(name):
    """required + optional keys of one declared event."""
    spec = EVENTS[name]
    return tuple(spec["required"]) + tuple(spec["optional"])


def required_keys(name):
    return tuple(EVENTS[name]["required"])


def injectable_fault_kinds():
    """Declaration-ordered LIGHTGBM_TPU_FAULT_INJECT kinds."""
    return tuple(FAULT_KINDS)


def one_shot_fault_kinds():
    """Kinds stripped from the inject spec after a restart."""
    return tuple(k for k, spec in FAULT_KINDS.items()
                 if spec["one_shot"])


def fault_event_kinds():
    """Every legal ``{"event": "fault"}`` kind string."""
    return tuple(FAULT_KINDS) + tuple(FAULT_EVENT_KINDS)
