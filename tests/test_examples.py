"""The examples/ config-file workflows must actually run — the
reference's test_consistency.py trains from examples/*/train.conf the
same way."""

import os
import shutil
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")

# scoped load (no sys.path pollution: a future examples/<name>.py must
# not shadow real modules for the rest of the suite)
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "examples_generate_data", os.path.join(EXAMPLES, "generate_data.py"))
_gd = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_gd)
GENERATORS = _gd.GENERATORS

from lightgbm_tpu.cli import main as cli_main  # noqa: E402

DATA_FILES = {
    "binary_classification": ("binary.train", "binary.test"),
    "regression": ("regression.train", "regression.test"),
    "multiclass_classification": ("multiclass.train", "multiclass.test"),
    "lambdarank": ("rank.train", "rank.test"),
}


@pytest.mark.parametrize("name", sorted(GENERATORS))
def test_example_config_trains_and_predicts(name, tmp_path, monkeypatch):
    src = os.path.join(EXAMPLES, name)
    for fn in os.listdir(src):
        if fn.endswith(".conf"):
            shutil.copy(os.path.join(src, fn), tmp_path / fn)
    GENERATORS[name](str(tmp_path))
    monkeypatch.chdir(tmp_path)
    cli_main(["config=train.conf", "num_trees=25", "verbosity=-1"])
    assert (tmp_path / "LightGBM_model.txt").exists()

    test_file = DATA_FILES[name][1]
    if (tmp_path / "predict.conf").exists():
        cli_main(["config=predict.conf"])
    else:
        cli_main(["task=predict", f"data={test_file}",
                  "input_model=LightGBM_model.txt",
                  "output_result=LightGBM_predict_result.txt"])
    preds = np.loadtxt(tmp_path / "LightGBM_predict_result.txt")
    raw = np.loadtxt(tmp_path / test_file, delimiter=",")
    y = raw[:, 0]
    if name == "multiclass_classification":
        assert preds.ndim == 2 and preds.shape[0] == len(y)
        acc = np.mean(np.argmax(preds, axis=1) == y)
        assert acc > 0.8, acc
    else:
        assert preds.shape == (len(y),)
        if name == "binary_classification":
            assert np.mean((preds > 0.5) == (y > 0.5)) > 0.85
        elif name == "regression":
            ss_res = np.sum((y - preds) ** 2)
            ss_tot = np.sum((y - y.mean()) ** 2)
            assert 1 - ss_res / ss_tot > 0.5
        else:  # lambdarank: scores must rank within queries
            qsizes = np.loadtxt(tmp_path / "rank.test.query",
                                dtype=int, ndmin=1)
            bounds = np.concatenate([[0], np.cumsum(qsizes)])
            assert bounds[-1] == len(y)
            ndcg_like = []
            for a, b in zip(bounds[:-1], bounds[1:]):
                order = np.argsort(-preds[a:b])
                ndcg_like.append(float(y[a:b][order[0]] >= 2))
            assert np.mean(ndcg_like) > 0.6
