"""The contract pass: TPL015-TPL018 verify the cross-process plane.

The fleet's only shared language is stringly-typed — JSONL
``{"event": ...}`` records, metrics-registry family names,
``LIGHTGBM_TPU_*`` env vars, and fault-kind strings.  These four
rules check every emission, bump, read, and injection site in the
package against the single-source registries in ``obs/schemas.py``
(literal-evaled straight out of the scanned tree's AST, so fixture
and mutation runs check THEIR OWN copy, never the installed one).

Pure stdlib, like the rest of the AST pass: the registries are
declared as pure literals exactly so this module never has to import
the package it is linting.

- **TPL015** event contract: every ``{"event": X}`` dict literal
  must emit a declared event, with no undeclared keys and (absent a
  ``**spread``) no missing required keys; consumers — any function
  that reads ``ev["event"]``/``ev.get("event")`` — may only compare
  against declared event names and only reference declared keys.
- **TPL016** metrics contract: every ``registry.counter/gauge/
  histogram`` / ``bump_counter`` family must be declared with the
  matching kind and label set; declared-but-never-bumped families
  and doc drift are findings.
- **TPL017** env contract: every ``LIGHTGBM_TPU_*`` name in the
  package must be declared, and a read site claiming a literal
  default must claim exactly the declared one — two sites
  disagreeing on a default can never both pass.
- **TPL018** fault contract: literal ``_KNOWN_KINDS`` /
  ``_ONE_SHOT_KINDS`` tuples, ``record_fault_event``-family call
  sites, ``FaultPlan`` gate calls, and the docs chaos matrix must
  all agree with the declared kind registry.

Whole-package aggregate checks (declared-but-never-X, doc drift)
anchor on ``obs/schemas.py`` and only run when that file is in the
reporting scope — a ``--changed`` slice that never touched the
registry cannot produce (or --strict-fail on) them.
"""

from __future__ import annotations

import ast
import os
import re
from typing import (Any, Dict, FrozenSet, Iterator, List, Optional,
                    Set, Tuple)

from .astscan import ModuleScan, dotted_of, literal_str_tuple
from .rules import Finding, LintContext, Rule

__all__ = ["CONTRACT_RULES", "SCHEMAS_RELPATH", "load_contracts"]

#: where the registries live, package-relative (fixture trees carry
#: their own mini copy under the same tail path)
SCHEMAS_RELPATH = "obs/schemas.py"

#: the five registry dicts the loader literal-evals
_REGISTRY_NAMES = ("EVENTS", "METRICS", "EXPORT_FAMILIES", "ENV_VARS",
                   "FAULT_KINDS", "FAULT_EVENT_KINDS")

_ENV_NAME_RE = re.compile(r"^LIGHTGBM_TPU_[A-Z0-9_]+$")


class Contracts:
    """The literal-evaled registries plus anchor linenos."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.events: Dict[str, dict] = {}
        self.metrics: Dict[str, dict] = {}
        self.export_families: Dict[str, dict] = {}
        self.env_vars: Dict[str, dict] = {}
        self.fault_kinds: Dict[str, dict] = {}
        self.fault_event_kinds: Dict[str, dict] = {}
        self.linenos: Dict[str, int] = {}      # registry name -> line
        self.anchor: Optional[ast.AST] = None  # first registry assign

    @property
    def all_event_keys(self) -> FrozenSet[str]:
        keys: Set[str] = set()
        for spec in self.events.values():
            keys.update(spec.get("required", ()))
            keys.update(spec.get("optional", ()))
        return frozenset(keys)

    def anchor_node(self, registry: str) -> ast.AST:
        node = ast.Module(body=[], type_ignores=[])
        node.lineno = self.linenos.get(registry, 1)
        node.col_offset = 0
        return node


def load_contracts(ctx: LintContext) -> Optional[Contracts]:
    """Find and literal-eval ``obs/schemas.py`` in the scanned tree.

    Returns None (contract rules no-op) when the tree carries no
    registry module — single-file fixture slices for the other rules
    must not drown in contract findings.
    """
    cache = getattr(ctx, "_contracts_cache", _MISSING)
    if cache is not _MISSING:
        return cache
    scan = _schemas_scan(ctx)
    out: Optional[Contracts] = None
    if scan is not None:
        out = Contracts(scan.relpath)
        for node in scan.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if name not in _REGISTRY_NAMES:
                continue
            try:
                value = ast.literal_eval(node.value)
            except ValueError:
                # non-literal registry: the single-source contract is
                # itself broken; surface it through TPL015
                out.linenos.setdefault(name, node.lineno)
                continue
            out.linenos[name] = node.lineno
            if out.anchor is None:
                out.anchor = node
            setattr(out, _ATTR_OF[name], value)
    ctx._contracts_cache = out            # type: ignore[attr-defined]
    return out


_MISSING = object()
_ATTR_OF = {"EVENTS": "events", "METRICS": "metrics",
            "EXPORT_FAMILIES": "export_families",
            "ENV_VARS": "env_vars", "FAULT_KINDS": "fault_kinds",
            "FAULT_EVENT_KINDS": "fault_event_kinds"}


def _schemas_scan(ctx: LintContext) -> Optional[ModuleScan]:
    for rel, scan in ctx.scans.items():
        if rel == SCHEMAS_RELPATH or rel.endswith("/" + SCHEMAS_RELPATH):
            return scan
    return None


def _site_scans(ctx: LintContext) -> Iterator[ModuleScan]:
    """Scans the per-site checks REPORT over: the rule scope minus
    the registry module itself (its dict keys are the declarations,
    not use sites)."""
    for scan in ctx.scoped_scans():
        if not _is_schemas(scan.relpath):
            yield scan


def _all_scans(ctx: LintContext) -> Iterator[ModuleScan]:
    """Scans the aggregate COLLECTION passes cover: everything parsed
    (a ``--changed`` run still parses the whole package), minus the
    registry module."""
    for rel in sorted(ctx.scans):
        if not _is_schemas(rel):
            yield ctx.scans[rel]


def _is_schemas(relpath: str) -> bool:
    return relpath == SCHEMAS_RELPATH \
        or relpath.endswith("/" + SCHEMAS_RELPATH)


def _docs_text(ctx: LintContext, filename: str) -> Optional[str]:
    """docs/<filename> next to the scanned package, when it exists
    (fixture and mutation trees have no docs/ — doc checks skip)."""
    root = getattr(ctx, "root", "") or ""
    if not root:
        return None
    path = os.path.join(os.path.dirname(os.path.abspath(root)),
                        "docs", filename)
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


def _mentions(text: str, token: str) -> bool:
    return re.search(r"(?<![A-Za-z0-9_])" + re.escape(token)
                     + r"(?![A-Za-z0-9_])", text) is not None


def _walk_skipping_nested(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs
    (they are analyzed as their own functions)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(child))


def _func_bodies(scan: ModuleScan) -> Iterator[Tuple[str, ast.AST]]:
    for qual, info in scan.funcs.items():
        yield qual, info.node


def _key_access(node: ast.AST) -> Optional[Tuple[str, str, ast.AST]]:
    """``(var, key, node)`` for ``var["key"]`` or ``var.get("key"...)``
    on a bare Name, else None."""
    if isinstance(node, ast.Subscript) \
            and isinstance(node.value, ast.Name) \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str):
        return (node.value.id, node.slice.value, node)
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" \
            and isinstance(node.func.value, ast.Name) \
            and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return (node.func.value.id, node.args[0].value, node)
    return None


def _event_access(node: ast.AST) -> bool:
    """Is ``node`` an ``<expr>["event"]`` / ``<expr>.get("event")``
    read on ANY receiver expression?"""
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and node.slice.value == "event":
        return True
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and bool(node.args)
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "event")


# ---------------------------------------------------------------------
class EventContract(Rule):
    """TPL015: emitted and consumed JSONL events match the registry."""

    id = "TPL015"
    title = "JSONL event outside the declared schema registry"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        c = load_contracts(ctx)
        if c is None:
            return
        if not c.events and "EVENTS" in c.linenos:
            yield self._finding(
                ctx, c.relpath, c.anchor_node("EVENTS"), "EVENTS",
                "EVENTS is not a pure literal dict — the contract "
                "lint cannot read it (keep the registry "
                "literal-evalable)")
            return
        for scan in _site_scans(ctx):
            yield from self._check_emissions(ctx, scan, c)
            yield from self._check_consumers(ctx, scan, c)
        if _is_schemas_in_scope(ctx):
            yield from self._aggregates(ctx, c)

    # -- emission sites ------------------------------------------------
    def _check_emissions(self, ctx: LintContext, scan: ModuleScan,
                         c: Contracts) -> Iterator[Finding]:
        for name, keys, spread, node in _emissions(scan.tree):
            spec = c.events.get(name)
            if spec is None:
                yield self._finding(
                    ctx, scan.relpath, node, f"event:{name}",
                    f'dict literal emits undeclared event "{name}" — '
                    f"declare it in {SCHEMAS_RELPATH} EVENTS (or fix "
                    f"the name)")
                continue
            required = set(spec.get("required", ()))
            allowed = required | set(spec.get("optional", ()))
            extra = sorted(keys - allowed)
            if extra:
                yield self._finding(
                    ctx, scan.relpath, node, f"event:{name}:keys",
                    f'"{name}" event emits undeclared key(s) '
                    f"{', '.join(extra)} — declare them in "
                    f"{SCHEMAS_RELPATH} EVENTS[{name!r}]")
            if not spread:
                missing = sorted(required - keys)
                if missing:
                    yield self._finding(
                        ctx, scan.relpath, node,
                        f"event:{name}:missing",
                        f'"{name}" event omits required key(s) '
                        f"{', '.join(missing)} (no **spread fills "
                        f"them)")

    # -- consumer sites ------------------------------------------------
    def _check_consumers(self, ctx: LintContext, scan: ModuleScan,
                         c: Contracts) -> Iterator[Finding]:
        union_keys = c.all_event_keys
        for qual, fnode in _func_bodies(scan):
            accesses: List[Tuple[str, str, ast.AST]] = []
            compares: List[Tuple[str, ast.AST]] = []
            for node in _walk_skipping_nested(fnode):
                acc = _key_access(node)
                if acc is not None:
                    accesses.append(acc)
                if isinstance(node, ast.Compare) \
                        and _event_access(node.left):
                    for comp in node.comparators:
                        for s in _const_strs(comp):
                            compares.append((s, node))
            event_vars = {var for var, key, _ in accesses
                          if key == "event"}
            for name, node in compares:
                if name not in c.events:
                    yield self._finding(
                        ctx, scan.relpath, node, f"consumes:{name}",
                        f'consumer compares against undeclared event '
                        f'name "{name}" — no declared emitter '
                        f"produces it", func=qual)
            seen: Set[str] = set()
            for var, key, node in accesses:
                # leading-underscore keys are consumer-local
                # annotations (e.g. load_spans' "_stream" clock-domain
                # tag), never wire keys — exempt by convention
                if var not in event_vars or key == "event" \
                        or key.startswith("_") \
                        or key in union_keys or key in seen:
                    continue
                seen.add(key)
                yield self._finding(
                    ctx, scan.relpath, node, f"consumes-key:{key}",
                    f'consumer references key "{key}" that no '
                    f"declared event emits — dead read or schema "
                    f"drift", func=qual)

    # -- whole-tree aggregates ----------------------------------------
    def _aggregates(self, ctx: LintContext,
                    c: Contracts) -> Iterator[Finding]:
        emitted: Set[str] = set()
        for scan in _all_scans(ctx):
            for name, _, _, _ in _emissions(scan.tree):
                emitted.add(name)
        for name in sorted(set(c.events) - emitted):
            yield self._finding(
                ctx, c.relpath, c.anchor_node("EVENTS"),
                f"unemitted:{name}",
                f'event "{name}" is declared but no dict literal in '
                f"the package emits it — stale registry entry")
        docs = _docs_text(ctx, "OBSERVABILITY.md")
        if docs is not None:
            for name in sorted(c.events):
                if not _mentions(docs, name):
                    yield self._finding(
                        ctx, c.relpath, c.anchor_node("EVENTS"),
                        f"undocumented-event:{name}",
                        f'event "{name}" is missing from '
                        f"docs/OBSERVABILITY.md — regenerate with "
                        f"tools/gen_obs_docs.py --write")


def _is_schemas_in_scope(ctx: LintContext) -> bool:
    return any(_is_schemas(rel) for rel in ctx.scope)


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, str):
                out.append(elt.value)
        return out
    return []


def _emissions(tree: ast.AST) -> Iterator[
        Tuple[str, Set[str], bool, ast.AST]]:
    """``(event_name, literal_keys, has_spread, node)`` for every
    ``{"event": "X", ...}`` dict literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        name: Optional[str] = None
        keys: Set[str] = set()
        spread = False
        for k, v in zip(node.keys, node.values):
            if k is None:                     # **spread
                spread = True
                continue
            if isinstance(k, ast.Constant) \
                    and isinstance(k.value, str):
                keys.add(k.value)
                if k.value == "event" \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    name = v.value
        if name is not None:
            yield name, keys, spread, node


# ---------------------------------------------------------------------
class MetricsContract(Rule):
    """TPL016: registry bumps match the declared metric families."""

    id = "TPL016"
    title = "metrics-registry family outside the declared registry"

    _METHODS = {"counter": "counter", "gauge": "gauge",
                "histogram": "histogram"}

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        c = load_contracts(ctx)
        if c is None:
            return
        for scan in _site_scans(ctx):
            if scan.relpath == "obs/registry.py":
                continue      # the implementation takes names as args
            yield from self._check_sites(ctx, scan, c, report=True)
        if _is_schemas_in_scope(ctx):
            yield from self._aggregates(ctx, c)

    def _aggregates(self, ctx: LintContext,
                    c: Contracts) -> Iterator[Finding]:
        bumped: Set[str] = set()
        for scan in _all_scans(ctx):
            if scan.relpath == "obs/registry.py":
                continue
            for f in self._check_sites(ctx, scan, c, report=False,
                                       bumped=bumped):
                pass
        for name in sorted(set(c.metrics) - bumped):
            yield self._finding(
                ctx, c.relpath, c.anchor_node("METRICS"),
                f"unbumped:{name}",
                f'metric family "{name}" is declared but never '
                f"bumped anywhere in the package — stale registry "
                f"entry")
        docs = _docs_text(ctx, "OBSERVABILITY.md")
        if docs is not None:
            for name in sorted(c.metrics):
                if not _mentions(docs, name):
                    yield self._finding(
                        ctx, c.relpath, c.anchor_node("METRICS"),
                        f"undocumented-metric:{name}",
                        f'metric family "{name}" is missing from '
                        f"docs/OBSERVABILITY.md — regenerate with "
                        f"tools/gen_obs_docs.py --write")

    def _check_sites(self, ctx: LintContext, scan: ModuleScan,
                     c: Contracts, report: bool,
                     bumped: Optional[Set[str]] = None
                     ) -> Iterator[Finding]:
        module_consts = _module_literals(scan.tree)
        bump_names = _bump_aliases(scan)
        for qual, fnode in list(_func_bodies(scan)) \
                + [("<module>", scan.tree)]:
            loops = _loop_bindings(fnode, module_consts)
            for node in (_walk_skipping_nested(fnode)
                         if qual != "<module>" else _module_walk(fnode)):
                if not isinstance(node, ast.Call):
                    continue
                kind, name_node, labels, starred = \
                    self._match_call(node, bump_names)
                if kind is None:
                    continue
                for f in self._check_one(ctx, scan, c, qual, node,
                                         kind, name_node, labels,
                                         starred, loops, bumped):
                    if report:
                        yield f

    def _match_call(self, node: ast.Call, bump_names: Set[str]):
        """(kind, name_node, label_names, has_starred) or Nones."""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in self._METHODS \
                and node.args:
            labels = {kw.arg for kw in node.keywords}
            return (self._METHODS[f.attr], node.args[0],
                    labels - {None}, None in labels)
        if isinstance(f, ast.Name) and f.id in bump_names \
                and node.args:
            labels = {kw.arg for kw in node.keywords}
            return ("counter", node.args[0], labels - {None},
                    None in labels)
        return (None, None, set(), False)

    def _check_one(self, ctx, scan, c, qual, node, kind, name_node,
                   labels, starred, loops, bumped) -> List[Finding]:
        names = _metric_names(name_node, loops)
        out: List[Finding] = []
        if names is None:
            # dynamic, unresolvable: only a finding when the receiver
            # is unmistakably the metrics registry (np.histogram &co
            # fall through here with non-str first args)
            dotted = dotted_of(node.func) or ""
            if "registry" in dotted.split("."):
                out.append(self._finding(
                    ctx, scan.relpath, node, "metric:<dynamic>",
                    "metric family name is dynamic and unresolvable "
                    "— use a literal (or an inline literal loop "
                    "tuple) so the contract lint can check it",
                    func=qual))
            return out
        prefix_match = isinstance(name_node, ast.JoinedStr)
        if prefix_match:
            resolved = [m for m in c.metrics if any(
                m.startswith(p) for p in names)]
            if not resolved:
                out.append(self._finding(
                    ctx, scan.relpath, node,
                    f"metric:{'|'.join(sorted(names))}*",
                    f"f-string metric name matches no declared "
                    f"family (literal prefix "
                    f"{', '.join(sorted(names))})", func=qual))
                return out
            names = resolved
        for name in sorted(set(names)):
            spec = c.metrics.get(name)
            if spec is None:
                out.append(self._finding(
                    ctx, scan.relpath, node, f"metric:{name}",
                    f'bump of undeclared metric family "{name}" — '
                    f"declare it in {SCHEMAS_RELPATH} METRICS",
                    func=qual))
                continue
            if bumped is not None:
                bumped.add(name)
            if spec.get("kind") != kind:
                out.append(self._finding(
                    ctx, scan.relpath, node, f"metric:{name}:kind",
                    f'"{name}" is declared a {spec.get("kind")} but '
                    f"bumped as a {kind}", func=qual))
            declared_labels = set(spec.get("labels", ()))
            if not starred and not prefix_match \
                    and labels != declared_labels:
                out.append(self._finding(
                    ctx, scan.relpath, node, f"metric:{name}:labels",
                    f'"{name}" bumped with labels '
                    f"{{{', '.join(sorted(labels)) or ''}}} but "
                    f"declared with "
                    f"{{{', '.join(sorted(declared_labels)) or ''}}}",
                    func=qual))
        return out


def _module_walk(tree: ast.AST) -> Iterator[ast.AST]:
    """Module statements outside any function body."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _module_literals(tree: ast.AST) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                out[node.targets[0].id] = ast.literal_eval(node.value)
            except ValueError:
                pass
    return out


def _bump_aliases(scan: ModuleScan) -> Set[str]:
    """Local names bound to obs.registry.bump_counter."""
    out = {"bump_counter"}
    for local, dotted in scan.imports.items():
        if dotted.endswith("bump_counter"):
            out.add(local)
    return out


def _loop_bindings(fnode: ast.AST,
                   module_consts: Dict[str, Any]
                   ) -> Dict[str, Set[str]]:
    """``for a, b in (("x", "y"), ...):`` -> {"a": {"x"}, "b": {"y"}}
    — how elastic.py names its per-sample gauge families."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(fnode):
        if not isinstance(node, ast.For):
            continue
        try:
            rows = ast.literal_eval(node.iter)
        except ValueError:
            rows = module_consts.get(node.iter.id) \
                if isinstance(node.iter, ast.Name) else None
        if not isinstance(rows, (tuple, list)) or not rows:
            continue
        targets = node.target.elts \
            if isinstance(node.target, ast.Tuple) else [node.target]
        for i, tgt in enumerate(targets):
            if not isinstance(tgt, ast.Name):
                continue
            vals = set()
            for row in rows:
                cell = row[i] if isinstance(row, (tuple, list)) \
                    and i < len(row) else row
                if isinstance(cell, str):
                    vals.add(cell)
            if vals:
                out.setdefault(tgt.id, set()).update(vals)
    return out


def _metric_names(name_node: ast.AST,
                  loops: Dict[str, Set[str]]
                  ) -> Optional[List[str]]:
    """Candidate family names of a bump's first argument: a literal
    str, an f-string (returns its literal PREFIXES for prefix
    matching), or a loop-bound name over a literal tuple table.
    None: dynamic, unresolvable."""
    if isinstance(name_node, ast.Constant):
        return [name_node.value] \
            if isinstance(name_node.value, str) else None
    if isinstance(name_node, ast.JoinedStr):
        prefix = ""
        for part in name_node.values:
            if isinstance(part, ast.Constant) \
                    and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return [prefix] if prefix else None
    if isinstance(name_node, ast.Name) and name_node.id in loops:
        return sorted(loops[name_node.id])
    return None


# ---------------------------------------------------------------------
class EnvContract(Rule):
    """TPL017: LIGHTGBM_TPU_* reads resolve to declared entries."""

    id = "TPL017"
    title = "LIGHTGBM_TPU_* env var outside the declared registry"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        c = load_contracts(ctx)
        if c is None:
            return
        for scan in _site_scans(ctx):
            yield from self._check_sites(ctx, scan, c)
        if _is_schemas_in_scope(ctx):
            yield from self._aggregates(ctx, c)

    def _check_sites(self, ctx: LintContext, scan: ModuleScan,
                     c: Contracts) -> Iterator[Finding]:
        seen_undeclared: Set[Tuple[str, int]] = set()
        for node in ast.walk(scan.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _ENV_NAME_RE.match(node.value) \
                    and node.value not in c.env_vars:
                key = (node.value, node.lineno)
                if key not in seen_undeclared:
                    seen_undeclared.add(key)
                    yield self._finding(
                        ctx, scan.relpath, node,
                        f"env:{node.value}",
                        f"undeclared env var {node.value} — declare "
                        f"it in {SCHEMAS_RELPATH} ENV_VARS",)
            if not isinstance(node, ast.Call):
                continue
            claimed = _env_default_claim(node)
            if claimed is None:
                continue
            name, default, site = claimed
            spec = c.env_vars.get(name)
            if spec is None:
                continue              # already reported as undeclared
            declared = spec.get("default")
            if declared is None or str(default) != str(declared):
                want = "no default (read bare and handle None at " \
                       "the site)" if declared is None \
                    else f"the declared default {declared!r}"
                yield self._finding(
                    ctx, scan.relpath, site, f"env:{name}:default",
                    f"{name} read with default {default!r} but the "
                    f"registry declares {want} — two sites "
                    f"disagreeing on a default can never both pass")

    def _aggregates(self, ctx: LintContext,
                    c: Contracts) -> Iterator[Finding]:
        referenced: Set[str] = set()
        for scan in _all_scans(ctx):
            for node in ast.walk(scan.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and _ENV_NAME_RE.match(node.value):
                    referenced.add(node.value)
        for name in sorted(set(c.env_vars) - referenced):
            yield self._finding(
                ctx, c.relpath, c.anchor_node("ENV_VARS"),
                f"unread:{name}",
                f"env var {name} is declared but never referenced "
                f"anywhere in the package — stale registry entry")
        docs = _docs_text(ctx, "OBSERVABILITY.md")
        if docs is not None:
            for name in sorted(c.env_vars):
                if not _mentions(docs, name):
                    yield self._finding(
                        ctx, c.relpath, c.anchor_node("ENV_VARS"),
                        f"undocumented-env:{name}",
                        f"env var {name} is missing from "
                        f"docs/OBSERVABILITY.md — regenerate with "
                        f"tools/gen_obs_docs.py --write")


def _env_default_claim(node: ast.Call
                       ) -> Optional[Tuple[str, Any, ast.AST]]:
    """``(name, default, node)`` when the call is
    ``<expr>.get/setdefault("LIGHTGBM_TPU_X", <literal>)`` with a
    non-None literal default."""
    f = node.func
    if not (isinstance(f, ast.Attribute)
            and f.attr in ("get", "setdefault")
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and _ENV_NAME_RE.match(node.args[0].value)
            and isinstance(node.args[1], ast.Constant)
            and node.args[1].value is not None):
        return None
    return (node.args[0].value, node.args[1].value, node)


# ---------------------------------------------------------------------
class FaultContract(Rule):
    """TPL018: fault kinds agree across plan, strip list, events,
    and the docs chaos matrix."""

    id = "TPL018"
    title = "fault kind outside the declared kind registry"

    #: writer call names -> index of the kind argument
    _WRITERS = {"append_fault_event": 1, "record_fault_event": 0,
                "_record_fault": 0, "_fault": 0}
    #: FaultPlan gate methods whose first arg is an injectable kind
    _GATES = ("fires", "take", "iters")

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        c = load_contracts(ctx)
        if c is None:
            return
        legal = set(c.fault_kinds) | set(c.fault_event_kinds)
        one_shot = {k for k, spec in c.fault_kinds.items()
                    if spec.get("one_shot")}
        for scan in _site_scans(ctx):
            yield from self._check_literals(ctx, scan, c, one_shot)
            yield from self._check_calls(ctx, scan, c, legal)
        if _is_schemas_in_scope(ctx):
            docs = _docs_text(ctx, "RESILIENCE.md")
            if docs is not None:
                for kind in sorted(c.fault_kinds):
                    if not _mentions(docs, kind):
                        yield self._finding(
                            ctx, c.relpath,
                            c.anchor_node("FAULT_KINDS"),
                            f"undocumented-fault:{kind}",
                            f'fault kind "{kind}" is missing from '
                            f"the docs/RESILIENCE.md chaos matrix")

    def _check_literals(self, ctx: LintContext, scan: ModuleScan,
                        c: Contracts,
                        one_shot: Set[str]) -> Iterator[Finding]:
        """Hand-maintained literal kind tuples (forks, fixtures) must
        match the registry; the shipped tree derives them from
        obs/schemas.py instead."""
        for node in scan.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            lit = literal_str_tuple(node.value)
            if lit is None:
                continue
            if name == "_KNOWN_KINDS" \
                    and set(lit) != set(c.fault_kinds):
                drift = sorted(set(lit) ^ set(c.fault_kinds))
                yield self._finding(
                    ctx, scan.relpath, node, "fault-kinds",
                    f"literal _KNOWN_KINDS disagrees with "
                    f"{SCHEMAS_RELPATH} FAULT_KINDS on "
                    f"{', '.join(drift)} — derive it from the "
                    f"registry (injectable_fault_kinds())")
            if name == "_ONE_SHOT_KINDS" and set(lit) != one_shot:
                drift = sorted(set(lit) ^ one_shot)
                yield self._finding(
                    ctx, scan.relpath, node, "one-shot-kinds",
                    f"literal _ONE_SHOT_KINDS disagrees with the "
                    f"one_shot classification in {SCHEMAS_RELPATH} "
                    f"on {', '.join(drift)} — derive it from the "
                    f"registry (one_shot_fault_kinds())")

    def _check_calls(self, ctx: LintContext, scan: ModuleScan,
                     c: Contracts,
                     legal: Set[str]) -> Iterator[Finding]:
        for node in ast.walk(scan.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr \
                if isinstance(node.func, ast.Attribute) \
                else (node.func.id
                      if isinstance(node.func, ast.Name) else None)
            if fname in self._WRITERS:
                idx = self._WRITERS[fname]
                kinds = _const_strs_deep(node.args[idx]) \
                    if len(node.args) > idx else []
                universe, where = legal, "FAULT_EVENT_KINDS"
            elif fname in self._GATES \
                    and isinstance(node.func, ast.Attribute) \
                    and node.args:
                kinds = _const_strs_deep(node.args[0])
                universe, where = set(c.fault_kinds), "FAULT_KINDS"
            else:
                continue
            for kind in kinds:
                if kind not in universe:
                    yield self._finding(
                        ctx, scan.relpath, node,
                        f"fault-kind:{kind}",
                        f'undeclared fault kind "{kind}" — declare '
                        f"it in {SCHEMAS_RELPATH} {where} (or fix "
                        f"the string)")


def _const_strs_deep(node: ast.AST) -> List[str]:
    """Every plausible kind literal inside an argument expression
    (plain constant, IfExp arms, tuples)."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, str) \
                and re.match(r"^[a-z][a-z0-9_]*$", sub.value):
            out.append(sub.value)
    return out


CONTRACT_RULES: List[Rule] = [EventContract(), MetricsContract(),
                              EnvContract(), FaultContract()]
