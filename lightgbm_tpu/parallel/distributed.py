"""Multi-host (multi-controller) initialization.

The reference reaches multi-machine training through ``Network::Init``
(/root/reference/src/network/linkers_socket.cpp:169 TCP mesh handshake /
linkers_mpi.cpp:16 MPI world) configured by ``machines``/``mlist`` +
``local_listen_port`` + ``num_machines``
(/root/reference/src/application/application.cpp:168-176; the Dask layer
assembles the same params, python-package/lightgbm/dask.py:495-520).

The TPU-native replacement is JAX's multi-controller runtime: every host
runs the same program, ``jax.distributed.initialize`` wires the
processes, and ``jax.devices()`` then spans all hosts so the ordinary
data-parallel Mesh (parallel/mesh.py) covers the pod — ICI inside a
slice, DCN across slices — with no linker layer at all.

Like the reference's socket linker (which retries its TCP handshake for
``time_out`` minutes), initialization here survives a coordinator that
is not up yet: connection-refused / unavailable errors are retried with
jittered exponential backoff, and the attempt count + total backoff are
surfaced as the ``init_retries`` / ``init_backoff_seconds`` registry
counters (docs/OBSERVABILITY.md). Knobs:

- ``LIGHTGBM_TPU_INIT_RETRIES`` — max retries after the first attempt
  (default 10),
- ``LIGHTGBM_TPU_INIT_BACKOFF`` — base backoff seconds (default 0.5;
  doubled per attempt, capped at 15 s, jittered to 50-100%),
- ``LIGHTGBM_TPU_INIT_TIMEOUT`` — per-attempt
  ``initialization_timeout`` passed to jax (seconds).

``init_distributed`` accepts BOTH the native JAX arguments and the
reference's machine-list vocabulary so a LightGBM-style launch config
ports directly:

    # reference-style (mlist.txt holds "host:port" lines, rank inferred)
    init_distributed(machine_list_file="mlist.txt", local_rank=0)
    # or explicit
    init_distributed(machines="10.0.0.1:12400,10.0.0.2:12400",
                     local_rank=1)
    # or native
    init_distributed(coordinator_address="10.0.0.1:12400",
                     num_processes=2, process_id=1)

Under the launch supervisor (``python -m lightgbm_tpu launch``,
resilience/elastic.py) the arguments can all be omitted: the supervisor
exports ``LIGHTGBM_TPU_COORDINATOR`` / ``LIGHTGBM_TPU_NUM_PROCS`` /
``LIGHTGBM_TPU_RANK`` and a bare ``init_distributed()`` picks them up.
"""

from __future__ import annotations

import os
import random
import re
import time
from typing import List, Optional, Tuple

from ..utils.log import log_info, log_warning

__all__ = ["init_distributed", "shutdown_distributed", "parse_machines"]

_INITIALIZED = False

#: backoff schedule bounds (seconds)
_BACKOFF_CAP = 15.0

#: substrings that mark an initialization error as transient — the
#: coordinator process is not up yet or is still binding its port
_RETRYABLE_MARKERS = ("connection refused", "unavailable",
                     "failed to connect", "connection reset",
                     "deadline_exceeded", "deadline exceeded")


def parse_machines(machines: Optional[str] = None,
                   machine_list_file: Optional[str] = None
                   ) -> List[Tuple[str, int]]:
    """Parse the reference's machine-list formats: a comma/newline
    separated ``host:port`` string (config ``machines``) or a file with
    one ``host port`` / ``host:port`` per line (``machine_list_file``,
    tests/distributed/_test_distributed.py:23-38). Blank entries and
    surrounding whitespace are ignored; a malformed entry raises
    ``ValueError`` naming it."""
    entries: List[str] = []
    if machines:
        entries = machines.replace("\n", ",").split(",")
    elif machine_list_file:
        with open(machine_list_file) as fh:
            entries = list(fh)
    out = []
    for raw in entries:
        e = raw.strip()
        if not e:
            continue
        parts = [p for p in re.split(r"[\s:]+", e) if p]
        if len(parts) > 2:
            raise ValueError(f"bad machine-list entry {e!r} "
                             "(expected 'host:port' or 'host port')")
        host = parts[0]
        port_str = parts[1] if len(parts) == 2 else "0"
        try:
            port = int(port_str)
        except ValueError:
            raise ValueError(f"bad port {port_str!r} in machine-list "
                             f"entry {e!r}") from None
        out.append((host, port))
    return out


def _is_retryable_init_error(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return any(m in msg for m in _RETRYABLE_MARKERS)


def _initialize_with_retry(init_kwargs: dict) -> None:
    """``jax.distributed.initialize`` with jittered exponential backoff
    on coordinator-not-up errors — the ``Network::Init`` retry loop
    (linkers_socket.cpp:169) for the multi-controller runtime. Raises
    ``LightGBMError`` with the attempt history when retries are
    exhausted."""
    import jax

    from ..basic import LightGBMError
    from ..obs.registry import registry
    from ..resilience.faults import FaultPlan

    plan = FaultPlan.from_env()
    max_retries = int(os.environ.get("LIGHTGBM_TPU_INIT_RETRIES", "10"))
    base = float(os.environ.get("LIGHTGBM_TPU_INIT_BACKOFF", "0.5"))
    timeout = os.environ.get("LIGHTGBM_TPU_INIT_TIMEOUT")
    if timeout:
        init_kwargs = dict(init_kwargs,
                           initialization_timeout=int(float(timeout)))
    total_wait = 0.0
    for attempt in range(max_retries + 1):
        try:
            plan.maybe_refuse_init()
            jax.distributed.initialize(**init_kwargs)
            if attempt:
                log_info(f"init_distributed: connected after {attempt} "
                         f"retried attempt(s), {total_wait:.2f}s of "
                         "backoff")
            return
        except Exception as e:
            if not _is_retryable_init_error(e):
                raise
            if attempt >= max_retries:
                raise LightGBMError(
                    "init_distributed: coordinator "
                    f"{init_kwargs.get('coordinator_address') or '(auto)'} "
                    f"still unreachable after {attempt + 1} attempts "
                    f"({total_wait:.2f}s of backoff): {e}. Is the "
                    "coordinator process up? Raise "
                    "LIGHTGBM_TPU_INIT_RETRIES / "
                    "LIGHTGBM_TPU_INIT_BACKOFF for slower bring-up."
                ) from e
            delay = min(_BACKOFF_CAP, base * (2.0 ** attempt))
            delay *= 0.5 + 0.5 * random.random()   # jitter: 50-100%
            registry.counter("init_retries").inc()
            registry.counter("init_backoff_seconds").inc(delay)
            log_warning(
                f"init_distributed: attempt {attempt + 1} failed "
                f"({e}); retrying in {delay:.2f}s")
            total_wait += delay
            time.sleep(delay)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     machines: Optional[str] = None,
                     machine_list_file: Optional[str] = None,
                     local_rank: Optional[int] = None) -> None:
    """Wire this process into a multi-host JAX runtime (the
    ``LGBM_NetworkInit`` / ``Network::Init`` analog), retrying with
    backoff while the coordinator comes up.

    With reference-style arguments, the first machine in the list is
    the coordinator and ``local_rank`` (or env ``LIGHTGBM_TPU_RANK``)
    selects this process's slot. A single-entry machine list is a
    no-op, matching ``num_machines=1``. With no arguments at all, the
    launch supervisor's ``LIGHTGBM_TPU_COORDINATOR`` /
    ``LIGHTGBM_TPU_NUM_PROCS`` / ``LIGHTGBM_TPU_RANK`` environment is
    honored; absent that too, ``jax.distributed.initialize()``
    discovers the topology itself (standard TPU pod launchers —
    GKE/queued resources).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    if coordinator_address is None and (machines or machine_list_file):
        mlist = parse_machines(machines, machine_list_file)
        if len(mlist) <= 1:
            return  # num_machines=1: nothing to wire
        host, port = mlist[0]
        coordinator_address = f"{host}:{port}"
        num_processes = len(mlist)
        if process_id is None:
            rank = local_rank if local_rank is not None else int(
                os.environ.get("LIGHTGBM_TPU_RANK") or -1)
            if rank < 0:
                raise ValueError(
                    "machine-list initialization needs local_rank (or "
                    "env LIGHTGBM_TPU_RANK) to identify this process")
            process_id = rank

    if coordinator_address is None and num_processes is None:
        # launch-supervisor environment (resilience/elastic.py)
        env_coord = os.environ.get("LIGHTGBM_TPU_COORDINATOR")
        if env_coord:
            nproc_env = os.environ.get("LIGHTGBM_TPU_NUM_PROCS")
            rank_env = os.environ.get("LIGHTGBM_TPU_RANK")
            if nproc_env is None or rank_env is None:
                raise ValueError(
                    "LIGHTGBM_TPU_COORDINATOR is set but "
                    "LIGHTGBM_TPU_NUM_PROCS / LIGHTGBM_TPU_RANK are "
                    "not — all three are required (the launch "
                    "supervisor exports them together; see "
                    "docs/RESILIENCE.md)")
            coordinator_address = env_coord
            num_processes = int(nproc_env)
            process_id = int(rank_env)

    if coordinator_address is None and num_processes is None:
        _initialize_with_retry({})
    else:
        _initialize_with_retry({
            "coordinator_address": coordinator_address,
            "num_processes": num_processes,
            "process_id": process_id})
    _INITIALIZED = True


def shutdown_distributed() -> None:
    """Tear the multi-controller runtime down (MPI_Finalize analog)."""
    global _INITIALIZED
    if not _INITIALIZED:
        return
    import jax

    jax.distributed.shutdown()
    _INITIALIZED = False
